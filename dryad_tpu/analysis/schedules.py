"""dryadlint layer 3 (dynamic half): deterministic-schedule race harness.

The static rules (analysis/concurrency.py) check LEXICAL lock
discipline; this module checks BEHAVIOR.  It runs the threaded host
plane's real classes — the serve micro-batcher, the fleet supervisor,
the obs registry, the fault injector — under a seeded cooperative
scheduler that serializes every thread and chooses, at each
synchronization point, which runnable thread proceeds.  Many seeds =
many interleavings; the same seed always replays the same interleaving
(bit-for-bit: the scheduler consumes one shared ``random.Random`` and
execution is fully serialized), so a failing schedule is a reproducible
artifact, not a flake.

How it works:

* ``Scheduler.instrument()`` patches ``threading.Lock/Event/Thread``,
  ``queue.Queue`` and ``time.sleep`` with shims that route every
  acquire/release/wait/set/put/get/spawn through the scheduler.  The
  code under test is UNMODIFIED — it constructs its locks and threads
  normally and gets the instrumented ones.
* Each managed thread runs on a real OS thread but is gated by a
  semaphore pair: exactly one runs at a time, and it hands control back
  at every schedule point.  With ``preempt_p > 0`` a ``sys.settrace``
  hook adds line-granular preemption inside the target modules, which is
  what lets the harness expose torn multi-statement updates (the
  registry histogram's counts/sum/count triple) that lock-op-only
  preemption can never interleave.
* Timeouts are VIRTUAL: a blocked-with-timeout task carries a deadline
  on the virtual clock, and deadlines fire only when no task is
  runnable — the deterministic model of "the timeout elapsed while
  everyone else was stuck", which is exactly the regime the r9 batcher
  stop()-timeout race needed.
* Every lock acquisition is recorded against the locks already held:
  after a run the union graph must be acyclic or ``check_lock_order``
  raises with BOTH acquisition stacks (the two halves of the deadlock).
  An actual runtime deadlock (nobody runnable, no deadline) raises
  ``DeadlockError`` with every blocked task's stack.

The DRILLS at the bottom re-run the recorded race classes the r13/r14
reviews caught by hand — batcher stop-vs-start-vs-predict, supervisor
monitor-vs-recovery-vs-crash, rolling push vs replica death, registry
record-vs-snapshot-vs-reset, injector concurrent fire — asserting each
subsystem's stated invariants.  ``run_ci_drills`` is what
``python -m dryad_tpu.analysis --ci`` executes (exit 6 on any failure);
the pytest suite additionally proves each drill still DETECTS its race
when the shipped fix is mechanically reverted (the mutation discipline
every dryadlint rule follows).
"""

from __future__ import annotations

import queue as _queue_mod
import random
import sys
import threading as _threading
import time as _time_mod
import traceback
from collections import deque
from typing import Callable, Optional

# the REAL primitives, captured before any instrument() patches the
# public names — the harness itself must never run on its own shims.
# Gating uses raw _thread locks as binary semaphores: the pure-Python
# threading.Semaphore/Event resolve ``Lock``/``Condition`` from the
# (patched) module globals at call time, so the harness cannot ride them.
import _thread

_RealThread = _threading.Thread
_RealEvent = _threading.Event
_real_allocate_lock = _thread.allocate_lock
_real_sleep = _time_mod.sleep
_THREADING_FILE = (_threading.__file__ or "threading.py").replace(
    ".pyc", ".py")


def _gate():
    """A raw lock in the 'parked' state: ``acquire()`` blocks until the
    peer ``release()``s — the ping-pong gate managed threads ride."""
    g = _real_allocate_lock()
    g.acquire()
    return g

_READY, _RUNNING, _BLOCKED, _DONE = "ready", "running", "blocked", "done"


class DeadlockError(AssertionError):
    """No task runnable, no pending virtual timeout — the report carries
    every blocked task's resource and stack."""


class LockOrderError(AssertionError):
    """The recorded acquisition graph contains a cycle — the report
    carries the two acquisition stacks of the closing edge."""


class ScheduleBudgetError(RuntimeError):
    """A schedule exceeded max_steps — a livelock or a runaway drill."""


class _ScheduleCancelled(BaseException):
    """Raised inside leftover task threads once the schedule ends (e.g.
    after a DeadlockError) so they unwind and exit instead of spinning
    on shim state nobody will ever change again.  BaseException so drill
    ``except Exception`` blocks cannot swallow it."""


def _trim_stack(limit: int = 18) -> str:
    """Current stack rendered without harness frames — the drill/code
    frames a human needs to localize a verdict."""
    frames = [f for f in traceback.extract_stack()
              if "analysis/schedules" not in f.filename.replace("\\", "/")]
    return "".join(traceback.format_list(frames[-limit:]))


def _creation_site() -> str:
    for f in reversed(traceback.extract_stack()):
        fn = f.filename.replace("\\", "/")
        if "analysis/schedules" not in fn and "/threading" not in fn:
            tail = fn.split("dryad_tpu/")[-1] if "dryad_tpu/" in fn \
                else fn.rsplit("/", 1)[-1]
            return f"{tail}:{f.lineno}"
    return "?"


class _Task:
    __slots__ = ("tid", "name", "sem", "state", "blocked_on", "deadline",
                 "timed_out", "error", "stack", "thread", "daemon",
                 "held_locks")

    def __init__(self, tid: int, name: str, daemon: bool = False):
        self.tid = tid
        self.name = name
        self.sem = _gate()
        self.state = _READY
        self.blocked_on = None
        self.deadline: Optional[float] = None
        self.timed_out = False
        self.error: Optional[BaseException] = None
        self.stack: Optional[str] = None
        self.thread: Optional[_RealThread] = None
        self.daemon = daemon
        self.held_locks: list = []


class Scheduler:
    """One deterministic schedule: seed -> interleaving."""

    def __init__(self, seed: int = 0, preempt_p: float = 0.0,
                 trace_files: tuple = (), max_steps: int = 50000):
        self.rng = random.Random(int(seed))
        self.seed = int(seed)
        self.preempt_p = float(preempt_p)
        self.trace_files = tuple(trace_files)
        self.max_steps = int(max_steps)
        self.steps = 0
        self.vtime = 0.0
        self.tasks: list[_Task] = []
        self._by_ident: dict[int, _Task] = {}
        self._sched_sem = _gate()
        self._running = False
        self._cancelled = False
        #: (holder_lock_name, acquired_lock_name) -> (holder's acquisition
        #: stack, this acquisition's stack) — the union graph check_lock_order
        #: walks for cycles
        self.lock_edges: dict = {}
        self._acq_stacks: dict = {}    # lock name -> last acquisition stack
        self._patched: list = []

    # ---- task plumbing -----------------------------------------------------
    def _cur(self) -> Optional[_Task]:
        return self._by_ident.get(_threading.get_ident())

    def spawn(self, fn: Callable, name: Optional[str] = None,
              daemon: bool = False) -> _Task:
        task = _Task(len(self.tasks), name or f"task{len(self.tasks)}",
                     daemon)
        self.tasks.append(task)

        def main() -> None:
            self._by_ident[_threading.get_ident()] = task
            task.sem.acquire()
            if self.preempt_p > 0 and self.trace_files:
                sys.settrace(self._trace)
            try:
                fn()
            except BaseException as e:   # noqa: BLE001 — replayed by run()
                task.error = e
            finally:
                sys.settrace(None)
                task.state = _DONE
                self._wake(("join", task))
                try:
                    self._sched_sem.release()
                except RuntimeError:
                    pass    # post-run zombie: nobody is waiting anymore

        t = _RealThread(target=main, daemon=True,
                        name=f"sched-{self.seed}-{task.name}")
        task.thread = t
        t.start()
        return task

    def _switch(self, state: str = _READY, blocked_on=None,
                timeout: Optional[float] = None) -> bool:
        """Hand control to the scheduler; returns True when the wait was
        resolved by a virtual timeout."""
        task = self._cur()
        if task is None:
            return False
        if not self._running:
            if self._cancelled:
                raise _ScheduleCancelled()
            return False
        task.state = state
        task.blocked_on = blocked_on
        task.deadline = (None if timeout is None
                         else self.vtime + max(float(timeout), 0.0))
        task.timed_out = False
        if state == _BLOCKED:
            task.stack = _trim_stack()
        self._sched_sem.release()
        task.sem.acquire()
        task.stack = None
        if self._cancelled:
            raise _ScheduleCancelled()
        return task.timed_out

    def pause(self) -> None:
        """An explicit schedule point (drill fakes call this to model 'any
        amount of real work happens here')."""
        self._switch()

    def sleep(self, seconds: float) -> None:
        """The time.sleep shim: a virtual-clock delay (schedule point even
        for sleep(0))."""
        if self._cur() is None:
            return
        self._switch(_BLOCKED, ("sleep", None), max(float(seconds), 1e-9))

    def _wake(self, resource) -> None:
        for t in self.tasks:
            if t.state == _BLOCKED and t.blocked_on == resource:
                t.state = _READY
                t.blocked_on = None
                t.deadline = None

    # ---- line-granular preemption ------------------------------------------
    def _trace(self, frame, event, arg):
        fn = frame.f_code.co_filename.replace("\\", "/")
        if event == "call":
            return self._trace if fn.endswith(self.trace_files) else None
        if event == "line" and self._running and fn.endswith(self.trace_files):
            if self.rng.random() < self.preempt_p:
                self._switch()
        return self._trace

    # ---- lock-order recording ----------------------------------------------
    def record_acquire(self, lock: "SchedLock", task: _Task) -> None:
        stack = _trim_stack()
        for held in task.held_locks:
            key = (held.name, lock.name)
            if key not in self.lock_edges:
                self.lock_edges[key] = (
                    self._acq_stacks.get(held.name, "<unknown>"), stack)
        self._acq_stacks[lock.name] = stack

    def check_lock_order(self) -> None:
        """Raise LockOrderError when the recorded acquisition graph has a
        cycle — with the two stacks that close it."""
        graph: dict[str, set] = {}
        for a, b in self.lock_edges:
            graph.setdefault(a, set()).add(b)
        color: dict[str, int] = {}
        path: list[str] = []

        def visit(node: str):
            color[node] = 1
            path.append(node)
            for nxt in sorted(graph.get(node, ())):
                if color.get(nxt) == 1:
                    cyc = path[path.index(nxt):] + [nxt]
                    edges = list(zip(cyc, cyc[1:]))
                    detail = "\n".join(
                        f"--- {a} held while acquiring {b} ---\n"
                        f"{self.lock_edges[(a, b)][1]}"
                        for a, b in edges)
                    raise LockOrderError(
                        "lock acquisition cycle (deadlock verdict): "
                        + " -> ".join(cyc) + "\n" + detail)
                if color.get(nxt) is None:
                    visit(nxt)
            path.pop()
            color[node] = 2

        for node in sorted(graph):
            if color.get(node) is None:
                visit(node)

    # ---- the schedule loop -------------------------------------------------
    def run(self) -> None:
        self._running = True
        try:
            while True:
                for t in self.tasks:
                    if t.error is not None:
                        raise t.error
                if all(t.state == _DONE for t in self.tasks
                       if not t.daemon):
                    break
                ready = [t for t in self.tasks if t.state == _READY]
                if not ready:
                    timed = [t for t in self.tasks
                             if t.state == _BLOCKED and t.deadline is not None]
                    if not timed:
                        raise DeadlockError(self._deadlock_report())
                    t = min(timed, key=lambda x: (x.deadline, x.tid))
                    self.vtime = max(self.vtime, t.deadline)
                    t.timed_out = True
                    t.state = _READY
                    t.blocked_on = None
                    t.deadline = None
                    continue
                self.steps += 1
                if self.steps > self.max_steps:
                    raise ScheduleBudgetError(
                        f"schedule exceeded {self.max_steps} steps "
                        f"(seed {self.seed}) — livelock or runaway drill")
                t = self.rng.choice(ready)
                t.state = _RUNNING
                t.sem.release()
                self._sched_sem.acquire()
        finally:
            self._running = False
            self._cancelled = True
            # wake every leftover task: its next _switch raises
            # _ScheduleCancelled, so it unwinds and exits instead of
            # spinning on shim state nobody will change again
            for t in self.tasks:
                if t.state != _DONE:
                    t.state = _DONE
                    t.sem.release()

    def _deadlock_report(self) -> str:
        lines = ["no runnable task and no pending virtual timeout — "
                 "deadlock:"]
        for t in self.tasks:
            if t.state == _BLOCKED:
                res = t.blocked_on
                what = res[0] if isinstance(res, tuple) else repr(res)
                target = res[1] if isinstance(res, tuple) else None
                tn = getattr(target, "name", "")
                lines.append(f"  task {t.name!r} blocked on {what} {tn}\n"
                             f"{t.stack or ''}")
        return "\n".join(lines)

    # ---- instrumentation ---------------------------------------------------
    def instrument(self) -> "_Instrument":
        return _Instrument(self)

    def monkeypatch(self, obj, attr: str, value) -> None:
        """Drill-scoped attribute patch, restored by run_schedule."""
        self._patched.append((obj, attr, getattr(obj, attr)))
        setattr(obj, attr, value)

    def restore_patches(self) -> None:
        while self._patched:
            obj, attr, old = self._patched.pop()
            setattr(obj, attr, old)


class _Instrument:
    """Context manager that swaps the public synchronization constructors
    for scheduler shims (and restores them)."""

    def __init__(self, sched: Scheduler):
        self._sched = sched
        self._saved: list = []

    def __enter__(self) -> "_Instrument":
        s = self._sched
        self._saved = [
            (_threading, "Lock", _threading.Lock),
            (_threading, "Event", _threading.Event),
            (_threading, "Thread", _threading.Thread),
            (_queue_mod, "Queue", _queue_mod.Queue),
            (_time_mod, "sleep", _time_mod.sleep),
            (_time_mod, "perf_counter", _time_mod.perf_counter),
            (_time_mod, "monotonic", _time_mod.monotonic),
        ]

        # threading.py's OWN internals (Thread._started, Condition inside
        # Semaphore, ...) resolve Lock/Event from the patched module
        # globals at call time — hand THEM the real primitives, shim
        # everything else
        def _from_threading_internals() -> bool:
            return sys._getframe(2).f_code.co_filename.endswith(
                ("threading.py", _THREADING_FILE))

        def lock_factory():
            if _from_threading_internals():
                return _real_allocate_lock()
            return SchedLock(s)

        def event_factory():
            if _from_threading_internals():
                return _RealEvent()
            return SchedEvent(s)

        def thread_factory(group=None, target=None, name=None, args=(),
                           kwargs=None, *, daemon=None):
            return SchedThread(s, target=target, name=name, args=args,
                               kwargs=kwargs, daemon=daemon)

        _threading.Lock = lock_factory
        _threading.Event = event_factory
        _threading.Thread = thread_factory
        _queue_mod.Queue = lambda maxsize=0: SchedQueue(s, maxsize)
        _time_mod.sleep = s.sleep
        # the clocks go VIRTUAL: wall time elapses between schedule points
        # by arbitrary real amounts (suspended threads), so any deadline
        # computed from a real clock would make schedules wall-dependent;
        # vtime advances only when a virtual timeout fires
        _time_mod.perf_counter = lambda: s.vtime
        _time_mod.monotonic = lambda: s.vtime
        return self

    def __exit__(self, *exc) -> None:
        for obj, attr, val in self._saved:
            setattr(obj, attr, val)


# ---------------------------------------------------------------------------
# shims


class SchedLock:
    """threading.Lock shim: scheduler-managed, order-recorded,
    non-reentrant (like the real thing)."""

    def __init__(self, sched: Scheduler, name: Optional[str] = None):
        self._sched = sched
        self.name = name or f"Lock@{_creation_site()}"
        self._owner: Optional[object] = None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        sched = self._sched
        task = sched._cur()
        if task is None or not sched._running:
            # setup/teardown phase: single-threaded direct execution
            if self._owner is not None:
                raise RuntimeError(
                    f"{self.name} contended outside the scheduler")
            self._owner = "setup"
            return True
        sched._switch()                      # acquire is a schedule point
        while self._owner is not None:
            if not blocking:
                return False
            timed_out = sched._switch(
                _BLOCKED, ("lock", self),
                timeout if timeout is not None and timeout > 0 else None)
            if timed_out:
                return False
        sched.record_acquire(self, task)
        self._owner = task
        task.held_locks.append(self)
        return True

    def release(self) -> None:
        sched = self._sched
        task = sched._cur()
        if task is None or not sched._running:
            self._owner = None
            return
        if self._owner is not task:
            raise RuntimeError(f"{self.name} released by a non-owner")
        task.held_locks.remove(self)
        self._owner = None
        sched._wake(("lock", self))
        sched._switch()                      # release is a schedule point

    def locked(self) -> bool:
        return self._owner is not None

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


class SchedEvent:
    """threading.Event shim with virtual-timeout wait."""

    def __init__(self, sched: Scheduler):
        self._sched = sched
        self._flag = False

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        self._flag = True
        self._sched._wake(("event", self))
        self._sched._switch()

    def clear(self) -> None:
        self._flag = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        sched = self._sched
        task = sched._cur()
        if task is None or not sched._running:
            return self._flag
        sched._switch()
        while not self._flag:
            if sched._switch(_BLOCKED, ("event", self), timeout):
                break
        return self._flag


class SchedQueue:
    """queue.Queue shim (FIFO, bounded, virtual timeouts; raises the real
    queue.Empty/queue.Full so caller except-clauses keep working)."""

    def __init__(self, sched: Scheduler, maxsize: int = 0):
        self._sched = sched
        self.maxsize = int(maxsize)
        self._items: deque = deque()

    def qsize(self) -> int:
        return len(self._items)

    def empty(self) -> bool:
        return not self._items

    def full(self) -> bool:
        return self.maxsize > 0 and len(self._items) >= self.maxsize

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        sched = self._sched
        task = sched._cur()
        if task is not None and sched._running:
            sched._switch()
        while self.full():
            if task is None or not sched._running or not block:
                raise _queue_mod.Full
            if sched._switch(_BLOCKED, ("queue_put", self), timeout):
                raise _queue_mod.Full
        self._items.append(item)
        sched._wake(("queue_get", self))

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        sched = self._sched
        task = sched._cur()
        if task is not None and sched._running:
            sched._switch()
        while not self._items:
            if task is None or not sched._running or not block:
                raise _queue_mod.Empty
            if sched._switch(_BLOCKED, ("queue_get", self), timeout):
                raise _queue_mod.Empty
        item = self._items.popleft()
        sched._wake(("queue_put", self))
        return item

    def get_nowait(self):
        return self.get(block=False)


class SchedThread:
    """threading.Thread shim: start() registers a managed task."""

    def __init__(self, sched: Scheduler, *, target=None, name=None,
                 args=(), kwargs=None, daemon=None):
        self._sched = sched
        self._target = target
        self._args = tuple(args)
        self._kwargs = dict(kwargs or {})
        self.name = name or f"thread-{id(self):x}"
        self.daemon = bool(daemon)
        self._task: Optional[_Task] = None

    def start(self) -> None:
        if self._task is not None:
            raise RuntimeError("threads can only be started once")
        self._task = self._sched.spawn(
            lambda: self._target(*self._args, **self._kwargs),
            name=self.name, daemon=self.daemon)
        if self._sched._cur() is not None:
            self._sched._switch()

    def is_alive(self) -> bool:
        return self._task is not None and self._task.state != _DONE

    def join(self, timeout: Optional[float] = None) -> None:
        sched = self._sched
        task = sched._cur()
        if self._task is None or self._task.state == _DONE:
            return
        if task is None or not sched._running:
            return
        sched._switch()
        while self._task.state != _DONE:
            if sched._switch(_BLOCKED, ("join", self._task), timeout):
                return


# ---------------------------------------------------------------------------
# running schedules


def _prewarm_defaults() -> None:
    """Materialize the process-wide singletons (default registry/health/
    watchdog/tripwire, numpy) BEFORE the shims go in: a lazy first touch
    from inside an instrumented drill would bake scheduler-bound shim
    locks into objects that outlive the schedule — the first run would
    then differ from every later one AND leak dead shims process-wide."""
    import numpy  # noqa: F401 — drills build Request rows

    from dryad_tpu.obs import spans  # noqa: F401
    from dryad_tpu.obs.health import default_health
    from dryad_tpu.obs.registry import default_registry
    from dryad_tpu.obs.tripwire import default_tripwire
    from dryad_tpu.obs.watchdog import default_watchdog

    default_registry()
    default_health()
    default_watchdog()
    default_tripwire()


def run_schedule(drill: Callable, seed: int, *, preempt_p: float = 0.0,
                 trace_files: tuple = (), max_steps: int = 50000) -> Scheduler:
    """One deterministic schedule of ``drill``: instrument, let the drill
    register tasks (and return an optional post-run check), run, verify
    the recorded lock order.  Raises on any invariant failure, deadlock,
    or lock-order cycle; returns the scheduler (steps/edges) on success.
    """
    _prewarm_defaults()
    from dryad_tpu.obs.registry import default_registry

    # the PROCESS default registry stays out of the schedule: a span
    # recorded from drilled code would otherwise lazily create families
    # (locks included) INSIDE the instrumented window — shim locks baked
    # into a process-wide singleton, and first-run schedules that differ
    # from every later one.  Drills that exercise the registry build
    # their own instance under instrumentation instead.
    reg = default_registry()
    was_enabled = reg.enabled
    reg.disable()
    sched = Scheduler(seed, preempt_p=preempt_p, trace_files=trace_files,
                      max_steps=max_steps)
    try:
        with sched.instrument():
            check = drill(sched)
            sched.run()
            if check is not None:
                check()
        sched.check_lock_order()
    finally:
        sched.restore_patches()
        if was_enabled:
            reg.enable()
    return sched


def run_schedules(drill: Callable, seeds, **kw) -> int:
    """Run ``drill`` across ``seeds``; raises (annotated with the seed) on
    the first failing schedule, returns the number run otherwise."""
    n = 0
    for seed in seeds:
        try:
            run_schedule(drill, seed, **kw)
        except BaseException as e:
            msg = f"[schedule seed {seed}] {e}"
            try:
                wrapped = type(e)(msg)
            except Exception:        # exotic exception signatures
                wrapped = AssertionError(msg)
            raise wrapped from e
        n += 1
    return n


# ---------------------------------------------------------------------------
# drills — the recorded race classes, as reusable schedule programs.
# Each returns a post-run check; invariants also assert inside tasks.


def drill_batcher_stop_start(sched: Scheduler):
    """MicroBatcher stop-vs-start-vs-predict — the r9 generation race.

    A dispatch wedges (gate event), stop() times out behind it, start()
    reinstates service, the dispatch un-wedges.  Invariant: the stale
    stop token must NOT kill the reinstated worker — a request submitted
    after reinstatement completes.  Mechanically reverting the fix
    (``_stop_live`` returning True for stale tokens) fails every
    schedule that reaches the reinstatement."""
    import numpy as np

    from dryad_tpu.serve.batcher import MicroBatcher, Request

    gate = _threading.Event()        # shimmed: created under instrument()
    entered = _threading.Event()
    results: dict = {}

    def dispatch(batch):
        entered.set()
        gate.wait()
        return [r.rows for r in batch]

    b = MicroBatcher(dispatch, max_wait_ms=1.0, queue_size=8)

    def submit(tag: str, timeout: float) -> None:
        req = Request(np.zeros((1, 2), np.float32))
        try:
            results[tag] = ("ok", b.submit(req, timeout=timeout))
        except BaseException as e:   # noqa: BLE001 — the verdict payload
            results[tag] = ("err", e)

    def service() -> None:
        b.start()
        submit("r1", 30.0)

    def controller() -> None:
        entered.wait()               # the worker is wedged in dispatch
        b.stop(timeout=0.05)         # join times out; token stays queued
        b.start()                    # deliberate reinstatement
        gate.set()                   # un-wedge the old dispatch
        submit("r2", 10.0)           # service must still be alive
        b.stop(timeout=30.0)         # clean shutdown drains

    sched.spawn(service, "service")
    sched.spawn(controller, "controller")

    def check() -> None:
        assert results.get("r1", ("?",))[0] == "ok", \
            f"r1 lost through the wedged dispatch: {results.get('r1')}"
        assert results.get("r2", ("?",))[0] == "ok", (
            "r9 stop/start generation race: a stale stop token killed the "
            f"reinstated worker and dropped r2 ({results.get('r2')})")

    return check


class _FakeReplicaProc:
    """Drill-controlled stand-in for fleet.replica.ReplicaProcess — same
    surface the supervisor touches, no subprocesses.  ``script`` hooks:
    ``on_start(proc)`` may block (a slow spawn)."""

    def __init__(self, sched: Scheduler, registry: list, script: dict,
                 make_argv, name="r0", env=None, startup_timeout_s=60.0,
                 log_dir=None):
        self._sched = sched
        self._script = script
        self.name = name
        self.env = dict(env or {})
        self.exit_code: Optional[int] = None
        self.health_status: "int | None" = 200
        self.host, self.port = "127.0.0.1", 1
        self.loaded_versions: list = []
        registry.append(self)

    def start(self):
        self._sched.pause()
        hook = self._script.get("on_start")
        if hook is not None:
            hook(self)
        return self

    def poll(self) -> Optional[int]:
        return self.exit_code

    @property
    def alive(self) -> bool:
        return self.exit_code is None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def clock_offset(self, timeout_s: float = 2.0) -> Optional[float]:
        # the r17 registration handshake: a fake replica has no /clock
        # (same shape as the protocol stubs' pre-r17 answer)
        return None

    def health(self, timeout_s: float = 2.0):
        self._sched.pause()
        if self.exit_code is not None:
            return None, 0.0
        return self.health_status, 0.0

    def stop(self, grace_s: float = 3.0) -> Optional[int]:
        if self.exit_code is None:
            self.exit_code = -15
        return self.exit_code

    def load_model(self, path, *, name=None, activate=True, auth_token=None,
                   timeout_s=120.0) -> int:
        self._sched.pause()
        if self.exit_code is not None:
            raise OSError(f"replica {self.name} is dead")
        self.loaded_versions.append(path)
        return 2


class _MemJournal:
    """In-memory journal with the RunJournal lock discipline — the drills
    assert on its event sequence."""

    GUARDED_BY = {"events": "_lock"}

    def __init__(self):
        self._lock = _threading.Lock()   # shimmed under instrument()
        self.events: list = []

    def event(self, kind: str, /, **fields) -> None:
        with self._lock:
            self.events.append((kind, fields))

    def kinds(self) -> list:
        with self._lock:
            return [k for k, _ in self.events]

    def close(self) -> None:
        pass


def _make_fleet(sched: Scheduler, script: dict, n: int = 2):
    from dryad_tpu.fleet import supervisor as sup_mod
    from dryad_tpu.obs.registry import Registry
    from dryad_tpu.resilience.policy import RetryPolicy

    procs: list = []

    def proc_factory(make_argv, *, name="r0", env=None,
                     startup_timeout_s=60.0, log_dir=None):
        return _FakeReplicaProc(sched, procs, script, make_argv, name=name,
                                env=env, startup_timeout_s=startup_timeout_s,
                                log_dir=log_dir)

    sched.monkeypatch(sup_mod, "ReplicaProcess", proc_factory)
    journal = _MemJournal()
    fs = sup_mod.FleetSupervisor(
        lambda i, pf: ["stub"], n,
        policy=RetryPolicy(retry_budget=3, backoff_base_s=0.01,
                           backoff_max_s=0.02),
        journal=journal, registry=Registry(enabled=False),
        probe_interval_s=0.05, probe_timeout_s=0.01,
        unhealthy_after=1, recycle_after=3, startup_timeout_s=5.0)
    return fs, journal, procs


def _wait_until(pred: Callable[[], bool], what: str,
                tries: int = 4000) -> None:
    for _ in range(tries):
        if pred():
            return
        _time_mod.sleep(0.01)        # shimmed: a virtual-clock tick
    raise AssertionError(f"condition never held: {what}")


def drill_supervisor_recovery(sched: Scheduler):
    """FleetSupervisor monitor-vs-recovery-vs-crash.

    Slot 0 crashes and its RESPAWN is wedged (slow spawn); slot 1 then
    crashes too.  Invariant: the monitor, not blocked by slot 0's
    recovery (the r14 async-recovery fix), detects and respawns slot 1
    while slot 0 is still wedged; both slots end healthy at generation 1
    with exactly one spawn per (slot, generation); stop() leaves no live
    process.  Mechanically reverting recovery to the monitor thread
    deadlocks the second detection and fails the drill."""
    hold = _threading.Event()

    def on_start(proc: _FakeReplicaProc) -> None:
        if proc.name == "r0g1":      # slot 0's respawn only
            hold.wait()

    fs, journal, procs = _make_fleet(sched, {"on_start": on_start}, n=2)

    def by_name(name: str) -> _FakeReplicaProc:
        for p in procs:
            if p.name == name:
                return p
        raise AssertionError(f"no spawned proc named {name}")

    def controller() -> None:
        fs.start()
        by_name("r0g0").exit_code = 23           # injected crash, slot 0
        _wait_until(lambda: any(p.name == "r0g1" for p in procs),
                    "slot 0 respawn dispatched")
        by_name("r1g0").exit_code = 23           # crash slot 1 MID-recovery
        _wait_until(lambda: fs.slots[1].generation == 1
                    and fs.slots[1].healthy,
                    "slot 1 respawned while slot 0 recovery is wedged")
        hold.set()                               # release slot 0's spawn
        _wait_until(lambda: fs.slots[0].generation == 1
                    and fs.slots[0].healthy, "slot 0 recovered")
        fs.stop()

    sched.spawn(controller, "controller")

    def check() -> None:
        names = [p.name for p in procs]
        assert len(names) == len(set(names)), \
            f"double-dispatched recovery: duplicate spawns {names}"
        assert sorted(names) == ["r0g0", "r0g1", "r1g0", "r1g1"], names
        for slot in fs.slots:
            assert not slot.recovering, f"{slot.name} left recovering"
        assert all(p.exit_code is not None for p in procs), \
            "stop() left a live replica process"
        kinds = journal.kinds()
        assert kinds.count("replica_crash") == 2, kinds
        assert kinds[-1] == "fleet_stop", kinds

    return check


def drill_rolling_push_vs_death(sched: Scheduler):
    """rolling_push vs router traffic vs a replica dying mid-push.

    Invariants: the drain always reaches zero (no in-flight request is
    dropped or leaked — final inflight == 0 on every slot), ``draining``
    is always restored, the dead slot's swap fails/skips cleanly while
    the other swaps, and the swap-lock/journal-lock runtime order stays
    acyclic (checked by the harness on every schedule)."""
    fs, journal, procs = _make_fleet(sched, {}, n=2)
    push_result: list = []

    def traffic() -> None:
        # a router-shaped client: mark in-flight, re-check routable (the
        # pick->inc window close), do some work, unmark
        for i in range(8):
            slot = fs.slots[i % 2]
            slot.inflight_inc()
            if not slot.routable:
                slot.inflight_dec()
                continue
            _time_mod.sleep(0.003)
            slot.inflight_dec()

    def pusher() -> None:
        _wait_until(lambda: fs._monitor is not None, "fleet started")
        push_result.append(fs.rolling_push("model-v2", drain_timeout_s=5.0))

    def killer() -> None:
        _wait_until(lambda: any(s.draining for s in fs.slots)
                    or push_result, "push began draining")
        fs.slots[1].proc.exit_code = 23

    def controller() -> None:
        fs.start()
        t = [sched.spawn(traffic, "traffic"), sched.spawn(pusher, "pusher"),
             sched.spawn(killer, "killer")]
        _wait_until(lambda: push_result, "push completed")
        _wait_until(lambda: all(x.state == _DONE for x in t),
                    "traffic drained")
        fs.stop()

    sched.spawn(controller, "controller")

    def check() -> None:
        assert push_result, "rolling_push never returned"
        res = push_result[0]
        for slot in fs.slots:
            assert slot.inflight == 0, \
                f"{slot.name} leaked inflight={slot.inflight}"
            assert not slot.draining, f"{slot.name} left draining"
        swapped = set(res["versions"])
        untouched = set(res["errors"]) | set(res["skipped"])
        assert swapped | untouched == {"r0", "r1"}, res
        assert "r0" in swapped, f"healthy slot failed to swap: {res}"

    return check


def drill_registry_snapshot(sched: Scheduler):
    """obs Registry record-vs-snapshot-vs-exposition-vs-reset.

    Invariant: a snapshot is INTERNALLY consistent — no torn labeled
    series: every histogram state satisfies count == sum(bucket counts)
    and (all observations being 1.0) sum == count; final totals are
    exact.  Runs with line-granular preemption inside obs/registry.py so
    a lock-free reader (the mutation the pytest suite seeds) tears."""
    from dryad_tpu.obs.registry import Registry

    reg = Registry(enabled=True)
    c = reg.counter("dryad_drill_total", "drill counter")
    h = reg.histogram("dryad_drill_lat", "drill histogram",
                      buckets=(0.5, 1.5, 2.5))
    tmp = reg.counter("dryad_drill_tmp_total", "reset fodder")
    snaps: list = []

    def writer(tag: str) -> Callable[[], None]:
        def run() -> None:
            for _ in range(6):
                c.labels(worker=tag).inc()
                h.labels(worker=tag).observe(1.0)
        return run

    def snapshotter() -> None:
        for _ in range(5):
            snaps.append(reg.snapshot())
            reg.exposition()

    def resetter() -> None:
        for _ in range(3):
            tmp.inc()
            reg.reset_prefix("dryad_drill_tmp")

    sched.spawn(writer("a"), "writer-a")
    sched.spawn(writer("b"), "writer-b")
    sched.spawn(snapshotter, "snapshotter")
    sched.spawn(resetter, "resetter")

    def check() -> None:
        final = reg.snapshot()
        for snap in snaps + [final]:
            for name, series in snap["histograms"].items():
                for lbl, st in series.items():
                    assert st["count"] == sum(st["counts"]), (
                        f"torn histogram snapshot {name}{{{lbl}}}: "
                        f"count={st['count']} counts={st['counts']}")
                    assert abs(st["sum"] - st["count"]) < 1e-9, (
                        f"torn histogram sum {name}{{{lbl}}}: {st}")
        for tag in ("a", "b"):
            key = f'worker="{tag}"'
            assert final["counters"]["dryad_drill_total"][key] == 6
            assert final["histograms"]["dryad_drill_lat"][key]["count"] == 6

    return check


def drill_loghist_scrape_tear(sched: Scheduler):
    """r17 log-histogram scrape-tear: concurrent O(1) observes into two
    registries (two "replicas") while a scraper snapshots both and
    EXACT-MERGES their series — the fleet router's /metrics shape.

    Invariants: every scraped state is internally consistent (count ==
    sum of bucket counts — a torn counts/sum/count triple is the race
    the line-granular preemption exposes), every merged state is too,
    and the FINAL merge is bitwise-equal to one histogram of the
    concatenated observations (dyadic values make the float sums
    associative, so "bitwise" is exact, not approximate)."""
    from dryad_tpu.obs.registry import (REQUEST_LATENCY, Registry,
                                        merge_hist_states)

    regs = [Registry(enabled=True), Registry(enabled=True)]
    fams = [r.log_histogram(REQUEST_LATENCY, "drill") for r in regs]
    values = [2.0 ** -k for k in range(1, 7)]      # dyadic: exact sums
    merges: list = []

    def writer(ri: int) -> Callable[[], None]:
        series = fams[ri].labels(priority="interactive", stage="total")

        def run() -> None:
            for v in values:
                series.observe(v)
        return run

    def scraper() -> None:
        for _ in range(5):
            blocks = [r.snapshot()["histograms"].get(REQUEST_LATENCY, {})
                      for r in regs]
            per_label: dict = {}
            for block in blocks:
                for lbl, st in block.items():
                    assert st["count"] == sum(st["counts"]), (
                        f"torn scraped state {lbl}: {st}")
                    per_label.setdefault(lbl, []).append(
                        (st["counts"], st["sum"], st["count"]))
            merged = {lbl: merge_hist_states(sts)
                      for lbl, sts in per_label.items()}
            for lbl, (counts, _s, n) in merged.items():
                assert n == sum(counts), f"torn merge {lbl}"
            merges.append(merged)

    sched.spawn(writer(0), "replica-a")
    sched.spawn(writer(1), "replica-b")
    sched.spawn(scraper, "scraper")

    def check() -> None:
        ref = Registry(enabled=True)
        series = ref.log_histogram(REQUEST_LATENCY, "ref").labels(
            priority="interactive", stage="total")
        for _ in regs:                     # the concatenated observations
            for v in values:
                series.observe(v)
        final = merge_hist_states(
            [f.labels(priority="interactive", stage="total").value()
             for f in fams])
        want = series.value()
        assert final[0] == want[0], "merged counts != concatenated"
        assert final[1] == want[1], "merged sum != concatenated (bitwise)"
        assert final[2] == want[2]
        assert merges, "the scraper never ran"

    return check


def drill_drift_window_tear(sched: Scheduler):
    """r18 drift-window tear: concurrent binned-batch/score observes into
    two replica DriftMonitors while a third rotates its two-epoch window
    and a scraper snapshots + EXACT-MERGES the export blocks — the fleet
    router's /drift shape, under line-granular preemption inside
    obs/drift.py.

    Invariants: every scraped block is internally consistent (each
    feature's window counts sum to the block's row count — a row
    increments exactly one bin per feature, so a torn counts-vs-rows
    read is the race the preemption exposes; the score state likewise),
    every merge of consistent blocks is consistent, and the FINAL merge
    equals one monitor fed the concatenated observations bitwise
    (integer counts — the merge-counts-never-ratios discipline), with
    PSI on the merge equal to PSI on the concatenation."""
    import numpy as np

    from dryad_tpu.obs.drift import (DriftMonitor, drift_report,
                                     merge_drift_states)
    from dryad_tpu.obs.registry import Registry

    ref = [[4, 4, 4, 4], [1, 2, 4, 9]]
    reg = Registry(enabled=False)
    mons = [DriftMonitor(ref, model="v1", window_rows=10 ** 6, registry=reg)
            for _ in range(2)]
    rot = DriftMonitor(ref, model="rot", window_rows=8, registry=reg)
    batches = [np.asarray([[0, 1], [1, 2], [2, 3]], np.uint8),
               np.asarray([[3, 0]], np.uint8),
               np.asarray([[2, 2], [1, 1]], np.uint8)]
    scores = [np.asarray([0.5, -0.5, 2.0]), np.asarray([0.25]),
              np.asarray([-2.0, 1.0])]
    merges: list = []

    def consistent(st: dict) -> None:
        for counts in st["features"]:
            assert sum(counts) == st["rows"], (
                f"torn drift block {st['model']}: rows={st['rows']} "
                f"counts={st['features']}")
        if st["score"] is not None:
            assert st["score"][2] == sum(st["score"][0]), (
                f"torn score state {st['model']}: {st['score']}")

    def writer(mi: int) -> Callable[[], None]:
        def run() -> None:
            for batch, sc in zip(batches, scores):
                mons[mi].observe_features(batch)
                mons[mi].observe_scores(sc)
        return run

    def rotator() -> None:
        # window 8 -> half 4: these 20 rows rotate the epochs repeatedly
        # while the scraper reads — a torn prev/cur swap breaks the
        # counts-vs-rows invariant
        for _ in range(4):
            rot.observe_features(batches[0])
            rot.observe_features(batches[2])

    def scraper() -> None:
        for _ in range(5):
            states = [m.export_state() for m in mons]
            for st in states + [rot.export_state()]:
                consistent(st)
            merged = merge_drift_states(states)
            for counts in merged["features"]:
                assert sum(counts) == merged["rows"], f"torn merge {merged}"
            merges.append(merged)

    sched.spawn(writer(0), "replica-a")
    sched.spawn(writer(1), "replica-b")
    sched.spawn(rotator, "rotator")
    sched.spawn(scraper, "scraper")

    def check() -> None:
        ref_mon = DriftMonitor(ref, model="ref", window_rows=10 ** 6,
                               registry=reg)
        for _ in mons:                     # the concatenated observations
            for batch, sc in zip(batches, scores):
                ref_mon.observe_features(batch)
                ref_mon.observe_scores(sc)
        merged = merge_drift_states([m.export_state() for m in mons])
        want = ref_mon.export_state()
        assert merged["features"] == want["features"], \
            "merged counts != concatenated"
        assert merged["rows"] == want["rows"]
        assert merged["score"][0] == want["score"][0], \
            "merged score counts != concatenated"
        assert merged["score"][2] == want["score"][2]
        assert (drift_report(merged)["psi_max"]
                == drift_report(want)["psi_max"]), \
            "PSI on the merge != PSI on the concatenation"
        assert merges, "the scraper never ran"

    return check


def drill_injector_concurrent_fire(sched: Scheduler):
    """FaultInjector concurrent fire — the r14 atomic check-and-clear.

    Four handler threads hit a ONE-SHOT reject point simultaneously.
    Invariant: it fires exactly once (one InjectedReject, one ``fired``
    record, zero left armed).  The non-atomic pre-fix version double-
    fires under line preemption (seeded by the pytest mutation test)."""
    from dryad_tpu.resilience.faults import (FaultInjector, FaultPoint,
                                             InjectedReject)

    inj = FaultInjector([FaultPoint(0, kind="reject_503", site="request")])
    rejections: list = []

    def caller(i: int) -> Callable[[], None]:
        def run() -> None:
            try:
                inj("request", i)
            except InjectedReject:
                rejections.append(i)
        return run

    for i in range(4):
        sched.spawn(caller(i), f"handler-{i}")

    def check() -> None:
        assert len(rejections) == 1, (
            f"one-shot injection fired {len(rejections)} times "
            f"(callers {sorted(rejections)}) — the armed check-and-clear "
            "is not atomic")
        assert len(inj.fired) == 1 and inj.pending == 0

    return check


def drill_scheduler_breach_vs_push(sched: Scheduler):
    """RetrainScheduler concurrent breach-vs-retrain-vs-push-vs-death (r19).

    The REAL RetrainScheduler and ProbationPublisher run against the live
    FleetSupervisor: two breach tasks each deliver 2 drift-breach events
    for the same model while the first admitted retrain (fake launcher on
    the virtual clock) is in flight, a concurrent rolling push swaps an
    unrelated model, and a replica dies mid-everything.  Invariants: the
    debounce admits EXACTLY ONE retrain for the burst (every other
    delivery journals ``retrain_skipped``), the completed generation
    settles to exactly one publish outcome, no in-flight state leaks, and
    the runtime lock order stays acyclic.  Mechanically splitting
    ``_admit``'s checks from its in-flight mark (the unlocked-streak
    mutation, seeded by the pytest mutation test) double-launches and
    fails the drill."""
    from dryad_tpu.continual.publish import ProbationPublisher
    from dryad_tpu.continual.scheduler import RetrainScheduler
    from dryad_tpu.obs.registry import Registry
    from dryad_tpu.resilience.policy import RetryPolicy

    fs, journal, procs = _make_fleet(sched, {}, n=2)
    launched: list = []

    def launch(model: str, gen: int, job: int, artifact: str):
        launched.append((model, gen, job))
        _time_mod.sleep(0.05)            # the retrain's virtual wall
        return True, f"{artifact}-g{gen}", ""

    def verdicts() -> dict:
        # clean traffic with rows flowing — probation must promote (the
        # rollback arm is the smoke's territory; here the race is the
        # debounce, not the verdict)
        return {"m": {"rows": 64, "breached": False, "sustained": False,
                      "psi_max": 0.01, "score_psi": 0.0, "streak": 0}}

    def push(path: str, model: str):
        res = fs.rolling_push(path, name=model, drain_timeout_s=5.0)
        errs = list(res.get("errors") or [])
        return (not errs), "; ".join(str(e) for e in errs)

    pub = ProbationPublisher(push, verdicts, journal=journal.event,
                             probation_polls=2, poll_interval_s=0.01,
                             clear_after=1, registry=Registry(enabled=False))
    rs = RetrainScheduler(
        {"m": "art-g0"}, launch, journal=journal.event, publisher=pub,
        policy=RetryPolicy(retry_budget=3, backoff_base_s=0.01),
        cooldown_s=1000.0, max_concurrent=1,
        has_profile=lambda p: True, registry=Registry(enabled=False))

    def breacher() -> None:
        for _ in range(2):
            rs.trigger("m", origin="drill")

    def pusher() -> None:
        _wait_until(lambda: fs._monitor is not None, "fleet started")
        fs.rolling_push("other-model", name="other", drain_timeout_s=5.0)

    def killer() -> None:
        _wait_until(lambda: launched, "retrain admitted")
        procs[1].exit_code = 23

    def controller() -> None:
        fs.start()
        tasks = [sched.spawn(breacher, "breach-a"),
                 sched.spawn(breacher, "breach-b"),
                 sched.spawn(pusher, "pusher"),
                 sched.spawn(killer, "killer")]
        _wait_until(lambda: all(x.state == _DONE for x in tasks),
                    "drill tasks done")
        _wait_until(lambda: not rs.state()["inflight"], "retrain drained")
        fs.stop()

    sched.spawn(controller, "controller")

    def check() -> None:
        kinds = journal.kinds()
        assert len(launched) == 1, (
            f"debounce double-launched: {launched} — _admit's check and "
            "in-flight mark are not one critical section")
        assert kinds.count("retrain_triggered") == 1, kinds
        assert kinds.count("retrain_complete") == 1, kinds
        assert kinds.count("retrain_skipped") == 3, kinds
        assert kinds.count("generation_rolled_back") == 0, kinds
        st = rs.state()
        assert not st["inflight"], f"in-flight state leaked: {st}"
        promoted = kinds.count("generation_promoted")
        failed = kinds.count("push_failed")
        # the killed replica may or may not be respawned by the time the
        # probation push lands — per seed exactly one outcome settles
        assert promoted + failed == 1, kinds
        if promoted:
            assert st["generation"]["m"] == 1, st
            assert st["artifacts"]["m"] == "art-g0-g1", st
        else:
            assert st["generation"]["m"] == 0, st
            assert st["artifacts"]["m"] == "art-g0", st

    return check


def drill_stream_prefetch(sched: Scheduler):
    """r20 Issue-17 data plane: ChunkPrefetcher producer-vs-consumer-vs-
    cancel.  Three REAL prefetchers run under line preemption: (a) a full
    sweep that must see every (i, chunk) pair exactly once, in order and
    untorn; (b) an early-abandon consumer whose mid-stream close() must
    unwedge a producer racing a depth-1 queue and reap its thread — the
    drain-outside-the-lock + cancellable-put contract (mechanically
    reverting the cancellable put wedges this arm on the sentinel put);
    (c) a reader that raises mid-stream — the error must surface in the
    consumer, never vanish into the producer thread."""
    from dryad_tpu.data.stream_dataset import ChunkPrefetcher

    state = {"full": [], "cancelled": False, "error": None}

    def full_sweep() -> None:
        pf = ChunkPrefetcher(lambda i: [i] * 4, 6, depth=2)
        try:
            for i, chunk in pf:
                assert chunk == [i] * 4, f"torn chunk pairing: {i}, {chunk}"
                state["full"].append(i)
        finally:
            pf.close()
        assert not pf._thread.is_alive(), "full-sweep producer leaked"

    def cancel_midstream() -> None:
        # 50 chunks against a depth-1 queue: the producer is essentially
        # always one put ahead, blocked, when close() lands
        pf = ChunkPrefetcher(lambda i: [i] * 4, 50, depth=1)
        it = iter(pf)
        next(it)
        pf.close()
        assert not pf._thread.is_alive(), (
            "close() left the producer wedged on the full queue")
        state["cancelled"] = True

    def error_stream() -> None:
        def read(i: int):
            if i == 2:
                raise RuntimeError("disk gone")
            return [i] * 4

        pf = ChunkPrefetcher(read, 5, depth=2)
        got = []
        try:
            for i, _chunk in pf:
                got.append(i)
        except RuntimeError as e:
            state["error"] = str(e)
        finally:
            pf.close()
        assert got == [0, 1], got

    sched.spawn(full_sweep, "full-sweep")
    sched.spawn(cancel_midstream, "cancel-midstream")
    sched.spawn(error_stream, "error-stream")

    def check() -> None:
        assert state["full"] == list(range(6)), (
            f"chunks lost/reordered/duplicated: {state['full']}")
        assert state["cancelled"], "mid-stream close never completed"
        assert state["error"] == "disk gone", state["error"]

    return check


def drill_capacity_breach_vs_push(sched: Scheduler):
    """r22 elastic capacity: CapacityController vs concurrent breach
    deliveries vs rolling push vs a replica crash.

    The REAL CapacityController drives the REAL FleetSupervisor slot
    registry (fake replica processes on the virtual clock): two scaler
    tasks poke the controller concurrently under sustained pressure
    while a rolling push swaps models and a replica dies mid-everything;
    once the new replica is routable the signal flips to sustained
    headroom and the pool must drain back down.  Invariants: EXACTLY ONE
    scale-up for the burst (every refused poke journals
    ``scale_skipped`` with a canonical reason), exactly one scale-down,
    the retired slot is never resurrected by the monitor, pokes at the
    min bound journal ``at-bound``, router-shaped traffic never leaks
    inflight through the drain, and the runtime lock order stays
    acyclic.  Mechanically splitting ``_admit``'s checks from its
    in-flight mark (the unlocked mutation in the pytest revert test)
    double-launches the spawn and fails the drill."""
    from dryad_tpu.fleet.autoscale import (SKIP_AT_BOUND, SKIP_COOLDOWN,
                                           SKIP_IN_FLIGHT, SKIP_SUSTAIN,
                                           CapacityController)
    from dryad_tpu.obs.registry import Registry

    fs, journal, procs = _make_fleet(sched, {}, n=2)
    sig = {"mode": "calm"}

    def signals() -> dict:
        # the drill's router stand-in: pressure = admission saturation,
        # headroom = near-empty fleet, calm = neither (streaks reset)
        mode = sig["mode"]
        inflight = {"pressure": 8, "headroom": 0, "calm": 4}[mode]
        slo = ({"interactive": {"breached": True, "sustained": True,
                                "p_ms": 900.0, "budget_ms": 250.0}}
               if mode == "pressure" else {})
        return {"slo": slo, "inflight": inflight, "max_inflight": 10,
                "slots": {}}

    ctrl = CapacityController(
        fs, signals, min_replicas=2, max_replicas=3,
        breach_after=1, idle_after=1,
        cooldown_up_s=1000.0, cooldown_down_s=1000.0,
        poll_interval_s=0.01, drain_timeout_s=5.0,
        registry=Registry(enabled=False))

    def by_name(name: str) -> _FakeReplicaProc:
        for p in procs:
            if p.name == name:
                return p
        raise AssertionError(f"no spawned proc named {name}")

    def scaler() -> None:
        for _ in range(2):
            ctrl.poke()
            _time_mod.sleep(0.003)

    def traffic() -> None:
        # router-shaped clients: mark in-flight, re-check routable (the
        # pick->inc window close), work, unmark — the retire drain must
        # wait these out, never drop them
        for i in range(12):
            slots = fs.slots
            slot = slots[i % len(slots)]
            slot.inflight_inc()
            if not slot.routable:
                slot.inflight_dec()
                continue
            _time_mod.sleep(0.003)
            slot.inflight_dec()

    def pusher() -> None:
        _wait_until(lambda: fs._monitor is not None, "fleet started")
        fs.rolling_push("model-v2", drain_timeout_s=5.0)

    def killer() -> None:
        _wait_until(lambda: any(p.name.startswith("r2") for p in procs),
                    "scale-up spawn dispatched")
        by_name("r0g0").exit_code = 23       # crash slot 0 mid-scale-up

    def downscaler() -> None:
        for _ in range(60):
            ctrl.poke()
            if (len(fs.slots) == 2
                    and ctrl.state()["action_in_flight"] is None):
                break
            _time_mod.sleep(0.01)
        ctrl.poke()                          # at the min bound now:
        ctrl.poke()                          # must journal ``at-bound``

    def controller() -> None:
        fs.start()
        sig["mode"] = "pressure"
        tasks = [sched.spawn(scaler, "scale-a"),
                 sched.spawn(scaler, "scale-b"),
                 sched.spawn(traffic, "traffic"),
                 sched.spawn(pusher, "pusher"),
                 sched.spawn(killer, "killer")]
        _wait_until(lambda: "scale_up" in journal.kinds(),
                    "the burst admitted a scale-up")
        _wait_until(lambda: len(fs.slots) == 3 and fs.slots[2].routable,
                    "new replica routable")
        _wait_until(lambda: fs.slots[0].generation == 1
                    and fs.slots[0].healthy, "crashed replica respawned")
        _wait_until(lambda: all(x.state == _DONE for x in tasks),
                    "pressure-phase tasks done")
        _wait_until(lambda: ctrl.state()["action_in_flight"] is None,
                    "scale-up settled")
        sig["mode"] = "headroom"
        down = sched.spawn(downscaler, "downscaler")
        _wait_until(lambda: down.state == _DONE, "drain-down done")
        ctrl.stop(timeout_s=1.0)
        fs.stop()

    sched.spawn(controller, "controller")

    def check() -> None:
        kinds = journal.kinds()
        assert kinds.count("scale_up") == 1, (
            f"capacity burst not exactly-one: {kinds} — _admit's check "
            "and in-flight mark are not one critical section")
        assert kinds.count("scale_down") == 1, kinds
        assert kinds.count("scale_failed") == 0, kinds
        assert kinds.count("replica_retired") == 1, kinds
        reasons = [f.get("reason") for k, f in journal.events
                   if k == "scale_skipped"]
        assert set(reasons) <= {SKIP_AT_BOUND, SKIP_COOLDOWN,
                                SKIP_IN_FLIGHT, SKIP_SUSTAIN}, reasons
        assert SKIP_AT_BOUND in reasons, (
            f"min-bound pokes never journaled at-bound: {reasons}")
        r2_spawns = [p.name for p in procs if p.name.startswith("r2")]
        assert r2_spawns == ["r2g0"], (
            f"retired slot resurrected: {r2_spawns}")
        names = [s.name for s in fs.slots]
        assert names == ["r0", "r1"], f"pool did not settle: {names}"
        for slot in fs.slots:
            assert not slot.retiring, f"{slot.name} left retiring"
            assert slot.inflight == 0, \
                f"{slot.name} leaked inflight={slot.inflight}"
        assert all(p.exit_code is not None for p in procs), \
            "stop() left a live replica process"
        st = ctrl.state()
        assert st["action_in_flight"] is None, st
        assert st["actions_total"] == {"up": 1, "down": 1}, st

    return check


#: name -> (drill, schedules to run in CI, preempt_p, trace file suffixes)
DRILLS: dict = {
    "batcher-stop-start": (drill_batcher_stop_start, 20, 0.1,
                           ("serve/batcher.py",)),
    "supervisor-recovery": (drill_supervisor_recovery, 10, 0.05,
                            ("fleet/supervisor.py",)),
    "rolling-push-vs-death": (drill_rolling_push_vs_death, 10, 0.05,
                              ("fleet/supervisor.py",)),
    "registry-snapshot": (drill_registry_snapshot, 20, 0.25,
                          ("obs/registry.py",)),
    "loghist-scrape-tear": (drill_loghist_scrape_tear, 20, 0.25,
                            ("obs/registry.py",)),
    "drift-window-tear": (drill_drift_window_tear, 15, 0.25,
                          ("obs/drift.py",)),
    "injector-concurrent-fire": (drill_injector_concurrent_fire, 20, 0.3,
                                 ("resilience/faults.py",)),
    "scheduler-breach-vs-push": (drill_scheduler_breach_vs_push, 10, 0.1,
                                 ("continual/scheduler.py",)),
    "stream-prefetch": (drill_stream_prefetch, 15, 0.25,
                        ("data/stream_dataset.py",)),
    "capacity-vs-breach-vs-push": (drill_capacity_breach_vs_push, 10, 0.1,
                                   ("fleet/autoscale.py",
                                    "fleet/supervisor.py")),
}


def run_ci_drills(schedules: Optional[int] = None, quiet: bool = False,
                  drills=None) -> list:
    """Run every drill across its seed range; returns failure strings
    (empty = pass).  This is the ``--ci``/``--concurrency`` entry."""
    failures = []
    if drills is not None:
        unknown = set(drills) - set(DRILLS)
        if unknown:
            # a typo'd --drill must fail loudly, never "pass" by running
            # zero drills (mirrors run_lint's unknown-rule rejection)
            raise ValueError(f"unknown drill(s): {sorted(unknown)} "
                             f"(known: {sorted(DRILLS)})")
    for name, (drill, n, preempt_p, trace_files) in sorted(DRILLS.items()):
        if drills is not None and name not in drills:
            continue
        count = int(schedules) if schedules is not None else n
        t0 = _time_mod.perf_counter()
        try:
            run_schedules(drill, range(count), preempt_p=preempt_p,
                          trace_files=trace_files)
        except BaseException as e:   # noqa: BLE001 — rendered as a verdict
            failures.append(f"{name}: {e}")
            if not quiet:
                print(f"drill {name}: FAIL — {e}")
            continue
        if not quiet:
            print(f"drill {name}: {count} schedules ok "
                  f"({_time_mod.perf_counter() - t0:.2f}s)")
    return failures
