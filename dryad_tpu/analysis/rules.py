"""The dryadlint rule catalog.

Every rule here machine-checks an invariant this repo MEASURED (CLAUDE.md
"measuring" + "lowering facts" sections) or pinned by construction
(STATUS round deltas), so the rule docstrings cite the discipline, not
style.  Migrated from scripts/ci.sh greps in round 11:

=====================  =====================================================
rule                   invariant
=====================  =====================================================
wired-grower-sort      nothing on the wired grower paths sorts rows or
                       reaches the retired per-level tile_plan helpers
no-block-until-ready   block_until_ready returns instantly through the axon
                       tunnel — a wait/throttle/wall built on it is a no-op
batcher-device-fetch   the serve dispatch loop never touches device results
                       (the ONE fetch lives in cache.execute_raw)
obs-jax-free           dryad_tpu/obs imports no jax, directly OR transitively
fleet-jax-free         dryad_tpu/fleet likewise (r14): the router/supervisor
                       must start and respawn while a device is wedged
jit-closure-constant   big arrays captured by jit closures become program
                       constants — remote compile rejects them (HTTP 413)
bench-real-fetch       timed fori programs end in a REAL host fetch
dead-perturbation      a perturbation consumed only through integer rounding
                       is a dead input — XLA hoists the stage (2x-fast lies)
introspect-compile-only  cost_analysis/memory_analysis/AOT-compile() live in
                       engine/introspect.py ONLY, and never in a loop or a
                       traced (fori/scan) body — the recompile tripwire
                       must never become a per-iteration host sync (r12)
unharnessed-timed-fori  the timed-fori discipline lives in exactly one
                       place (engine/probes.timed_fori, with the runtime
                       liveness proof); bench/profile scripts must not
                       re-copy it around a raw lax.fori_loop (r13)
=====================  =====================================================
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from dryad_tpu.analysis.importgraph import find_banned_chains
from dryad_tpu.analysis.lint import Rule, Violation, register

# ---------------------------------------------------------------------------
# AST helpers


def dotted(node: ast.AST) -> Optional[str]:
    """'jax.lax.sort' for Name/Attribute chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _calls(tree: ast.AST) -> Iterable[ast.Call]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            yield node


def _imports_of(tree: ast.AST, roots: tuple) -> Iterable[tuple[int, str]]:
    """(line, module) for any import whose root package is in ``roots`` —
    function-local imports included (callers that need only module-level
    edges use importgraph instead)."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.split(".")[0] in roots:
                    yield node.lineno, alias.name
        elif isinstance(node, ast.ImportFrom) and node.level == 0:
            mod = node.module or ""
            if mod.split(".")[0] in roots:
                yield node.lineno, mod


# ---------------------------------------------------------------------------
# wired-grower-sort

_SORTISH = {"sort", "argsort", "lexsort", "sort_key_val", "top_k"}


def _check_wired_grower(path, src, tree):
    out = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            # aliased imports would dodge the Name/Attribute scan below
            # (`from ...plan import tile_plan as _tp`)
            names = [getattr(node, "module", None) or ""]
            for alias in node.names:
                names += [alias.name, alias.asname or ""]
            for n in names:
                if "tile_plan" in n:
                    out.append(Violation(
                        "wired-grower-sort", path, node.lineno,
                        f"import of retired per-level sort helper {n!r} in "
                        "a wired grower — the per-level sort/gather is gone "
                        "(r6/r10); route legacy configs through "
                        "build_hist_segmented"))
        if isinstance(node, (ast.Attribute, ast.Name)):
            leaf = node.attr if isinstance(node, ast.Attribute) else node.id
            if "tile_plan" in leaf:
                out.append(Violation(
                    "wired-grower-sort", path, node.lineno,
                    f"reference to retired per-level sort helper {leaf!r} — "
                    "the wired growers' whole point is that the per-level "
                    "sort/gather is gone (r6/r10); route legacy configs "
                    "through build_hist_segmented"))
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            leaf = name.rsplit(".", 1)[-1] if name else None
            if leaf in _SORTISH:
                out.append(Violation(
                    "wired-grower-sort", path, node.lineno,
                    f"{name}(...) in a wired grower — nothing on the wired "
                    "path sorts rows (the layout replaces the per-level "
                    "sort); if this sorts an (L,)-sized slot table, waive "
                    "with the shape rationale"))
    return out


register(Rule(
    name="wired-grower-sort",
    doc="wired growers must not sort rows nor reach tile_plan helpers",
    targets=("dryad_tpu/engine/levelwise.py",
             "dryad_tpu/engine/leafwise_fast.py"),
    check=_check_wired_grower,
))


# ---------------------------------------------------------------------------
# no-block-until-ready

def _check_block_until_ready(path, src, tree):
    out = []
    for node in ast.walk(tree):
        hit = (isinstance(node, ast.Attribute)
               and node.attr == "block_until_ready")
        if hit:
            out.append(Violation(
                "no-block-until-ready", path, node.lineno,
                "block_until_ready returns instantly through the axon "
                "tunnel (STATUS r5) — any wait/throttle/wall built on it "
                "is a no-op; use a real fetch (float(x) / np.asarray)"))
    return out


register(Rule(
    name="no-block-until-ready",
    doc="serve/resilience/obs/fleet/continual/bench must never sync on "
        "block_until_ready",
    targets=("dryad_tpu/serve/**", "dryad_tpu/resilience/**",
             "dryad_tpu/obs/**", "dryad_tpu/fleet/**",
             "dryad_tpu/continual/**", "bench.py", "scripts/*.py"),
    check=_check_block_until_ready,
))


# ---------------------------------------------------------------------------
# batcher-device-fetch

def _check_batcher(path, src, tree):
    out = []
    for line, mod in _imports_of(tree, ("jax", "jaxlib")):
        out.append(Violation(
            "batcher-device-fetch", path, line,
            f"import {mod} in the serve batcher — the collect/dispatch "
            "loop is host-only; the single result fetch belongs in "
            "cache.execute_raw"))
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr in (
                "device_get", "asnumpy", "addressable_data"):
            out.append(Violation(
                "batcher-device-fetch", path, node.lineno,
                f".{node.attr} in the serve batcher — a fetch growing back "
                "into the dispatch loop serializes the overlapped pipeline"))
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name in ("np.asarray", "numpy.asarray", "np.array",
                        "numpy.array"):
                out.append(Violation(
                    "batcher-device-fetch", path, node.lineno,
                    f"{name}(...) in the serve batcher — materializing here "
                    "would fetch device buffers inside the dispatch loop"))
    return out


register(Rule(
    name="batcher-device-fetch",
    doc="serve/batcher.py stays fetch-free and jax-free",
    targets=("dryad_tpu/serve/batcher.py",),
    check=_check_batcher,
))


# ---------------------------------------------------------------------------
# obs-jax-free (direct bans per file + transitive import closure)

def _check_obs_direct(path, src, tree):
    out = []
    for line, mod in _imports_of(tree, ("jax", "jaxlib")):
        out.append(Violation(
            "obs-jax-free", path, line,
            f"import {mod} in dryad_tpu/obs — obs collectors are host-side "
            "only and the package is jax-free by lint (r9); record values "
            "the engine already fetched"))
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr in (
                "device_get", "addressable_data", "asnumpy"):
            out.append(Violation(
                "obs-jax-free", path, node.lineno,
                f".{node.attr} in dryad_tpu/obs — obs must never touch "
                "device buffers (CLAUDE.md never-fetch-per-iteration)"))
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name in ("np.asarray", "numpy.asarray"):
                out.append(Violation(
                    "obs-jax-free", path, node.lineno,
                    f"{name}(...) in dryad_tpu/obs — materializing arrays "
                    "here is the device-fetch shape the r9 lint bans"))
    return out


def _tree_check_obs(sources, tree):
    out = []
    chains = find_banned_chains(sorted(sources), tree,
                                banned_roots=("jax", "jaxlib"))
    for chain, banned in chains:
        entry = chain[0]
        out.append(Violation(
            "obs-jax-free", _module_rel(entry, tree), 1,
            "transitive jax import: " + " -> ".join(chain)
            + " — importing dryad_tpu.obs must not pull in jax "
            "(jax-free-by-construction contract, r9/r11)"))
    return out


def _module_rel(mod: str, tree) -> str:
    from dryad_tpu.analysis.importgraph import module_path_candidates

    for cand in module_path_candidates(mod):
        if tree.exists(cand):
            return cand
    return mod


register(Rule(
    name="obs-jax-free",
    doc="dryad_tpu/obs is jax-free, directly and transitively",
    targets=("dryad_tpu/obs/**",),
    check=_check_obs_direct,
    tree_check=_tree_check_obs,
))


# ---------------------------------------------------------------------------
# fleet-jax-free (r14) — the same contract as obs, for the same reason:
# the fleet router and supervisor are host-side process/socket machinery
# that must start, route, and respawn while a replica's device is wedged.
# A jax import here would (a) couple router startup to device init and
# (b) tempt a device fetch into the routing loop.  Direct bans are strict
# (lazy in-function imports included); the transitive check walks
# module-level imports — e.g. an innocent helper pulled from engine/
# would flag the whole chain.

def _check_fleet_direct(path, src, tree):
    out = []
    for line, mod in _imports_of(tree, ("jax", "jaxlib")):
        out.append(Violation(
            "fleet-jax-free", path, line,
            f"import {mod} in dryad_tpu/fleet — the fleet layer is "
            "host-side process/socket supervision and jax-free by lint "
            "(r14); replicas own the devices, the fleet owns processes"))
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr in (
                "device_get", "addressable_data", "asnumpy"):
            out.append(Violation(
                "fleet-jax-free", path, node.lineno,
                f".{node.attr} in dryad_tpu/fleet — the router/supervisor "
                "must never touch device buffers; every value crosses HTTP"))
    return out


def _tree_check_fleet(sources, tree):
    out = []
    chains = find_banned_chains(sorted(sources), tree,
                                banned_roots=("jax", "jaxlib"))
    for chain, banned in chains:
        entry = chain[0]
        out.append(Violation(
            "fleet-jax-free", _module_rel(entry, tree), 1,
            "transitive jax import: " + " -> ".join(chain)
            + " — importing dryad_tpu.fleet must not pull in jax "
            "(jax-free-by-construction contract, r14; import from the "
            "jax-free leaf modules — obs, resilience.faults/journal/"
            "policy — not the packages that wrap them)"))
    return out


register(Rule(
    name="fleet-jax-free",
    doc="dryad_tpu/fleet is jax-free, directly and transitively",
    targets=("dryad_tpu/fleet/**",),
    check=_check_fleet_direct,
    tree_check=_tree_check_fleet,
))


# ---------------------------------------------------------------------------
# continual-jax-free (r19) — the retrain scheduler and probation publisher
# live in the fleet control plane: they must tail the journal, debounce,
# launch, push, and roll back while a replica's (or the retrain worker's)
# device is wedged.  The retrain itself is a SUBPROCESS
# (`python -m dryad_tpu retrain`) — that is the only jax-importing piece
# of the continual loop, and it is outside this package by construction.

def _check_continual_direct(path, src, tree):
    out = []
    for line, mod in _imports_of(tree, ("jax", "jaxlib")):
        out.append(Violation(
            "continual-jax-free", path, line,
            f"import {mod} in dryad_tpu/continual — the scheduler/publisher "
            "are control-plane machinery and jax-free by lint (r19); the "
            "retrain worker subprocess owns the devices"))
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr in (
                "device_get", "addressable_data", "asnumpy"):
            out.append(Violation(
                "continual-jax-free", path, node.lineno,
                f".{node.attr} in dryad_tpu/continual — the control plane "
                "must never touch device buffers; artifacts cross the "
                "filesystem, verdicts cross HTTP"))
    return out


def _tree_check_continual(sources, tree):
    out = []
    chains = find_banned_chains(sorted(sources), tree,
                                banned_roots=("jax", "jaxlib"))
    for chain, banned in chains:
        entry = chain[0]
        out.append(Violation(
            "continual-jax-free", _module_rel(entry, tree), 1,
            "transitive jax import: " + " -> ".join(chain)
            + " — importing dryad_tpu.continual must not pull in jax "
            "(r19; the booster/mapper stay out — model_has_profile sniffs "
            "artifacts with numpy+json, the retrain worker subprocess does "
            "the loading)"))
    return out


register(Rule(
    name="continual-jax-free",
    doc="dryad_tpu/continual is jax-free, directly and transitively",
    targets=("dryad_tpu/continual/**",),
    check=_check_continual_direct,
    tree_check=_tree_check_continual,
))


# ---------------------------------------------------------------------------
# policy-jax-free (r23) — the calibration table keys dispatch decisions
# and must load in the fleet control plane (serve /stats, the supervisor)
# while a device is wedged; resolvers are pure dict-and-compare code.
# The ONE sanctioned exception is the lazy best-effort device_kind probe
# in policy/device.py, waived inline (and counted by the ratchet).

def _check_policy_direct(path, src, tree):
    out = []
    for line, mod in _imports_of(tree, ("jax", "jaxlib")):
        out.append(Violation(
            "policy-jax-free", path, line,
            f"import {mod} in dryad_tpu/policy — gate resolution is "
            "host-side table lookup and jax-free by lint (r23); the "
            "calibration SWEEP reaches devices only through "
            "engine/probes, imported lazily inside calibrate.run_sweep"))
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr in (
                "device_get", "addressable_data", "asnumpy"):
            out.append(Violation(
                "policy-jax-free", path, node.lineno,
                f".{node.attr} in dryad_tpu/policy — a gate resolver "
                "must never touch device buffers; walls arrive as floats "
                "from the probe harness"))
    return out


def _tree_check_policy(sources, tree):
    out = []
    chains = find_banned_chains(sorted(sources), tree,
                                banned_roots=("jax", "jaxlib"))
    for chain, banned in chains:
        entry = chain[0]
        out.append(Violation(
            "policy-jax-free", _module_rel(entry, tree), 1,
            "transitive jax import: " + " -> ".join(chain)
            + " — importing dryad_tpu.policy must not pull in jax (r23; "
            "probe/trends imports stay lazy inside the sweep functions)"))
    return out


register(Rule(
    name="policy-jax-free",
    doc="dryad_tpu/policy is jax-free, directly and transitively",
    targets=("dryad_tpu/policy/**",),
    check=_check_policy_direct,
    tree_check=_tree_check_policy,
))


# ---------------------------------------------------------------------------
# gate-through-policy (r23) — the dispatch-gate functions must read their
# thresholds from the policy calibration table, never from re-inlined
# literals: a constant hand-edited at ONE call site silently forks the
# gate from the committed table (and from every other caller), which is
# exactly the two-copy drift select_bins' r5 review caught.  Structural
# encoding widths stay at the call sites as NAMED module constants
# (levelwise._MAX_PACKED_BINS) — the rule flags folded int literals at or
# past 512 (the smallest calibrated threshold) inside the known gate
# functions only, so shape arithmetic like ``9 + F * itemsize`` passes.

_GATE_FUNCTIONS = {
    "partition_prefers_reduce", "hist_reduce_resolved",
    "deep_layout_supported", "leafwise_layout_supported",
    "resolve_backend", "stage_trees",
}
_GATE_LITERAL_FLOOR = 512


def _fold_int(node) -> Optional[int]:
    """Constant-fold an int expression (``1 << 15`` must not evade the
    rule by being spelled as a BinOp)."""
    if isinstance(node, ast.Constant) and type(node.value) is int:
        return node.value
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = _fold_int(node.operand)
        return -v if v is not None else None
    if isinstance(node, ast.BinOp):
        left, right = _fold_int(node.left), _fold_int(node.right)
        if left is None or right is None:
            return None
        try:
            if isinstance(node.op, ast.LShift):
                return left << right
            if isinstance(node.op, ast.Add):
                return left + right
            if isinstance(node.op, ast.Sub):
                return left - right
            if isinstance(node.op, ast.Mult):
                return left * right
            if isinstance(node.op, ast.Pow) and right < 64:
                return left ** right
            if isinstance(node.op, ast.FloorDiv) and right != 0:
                return left // right
        except (OverflowError, ValueError):
            return None
    return None


def _check_gate_literals(path, src, tree):
    out = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if fn.name not in _GATE_FUNCTIONS:
            continue
        # fold top-down and don't descend into folded expressions, so
        # `1 << 15` reports once (as 32768), not once per operand
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            folded = _fold_int(node) if isinstance(
                node, (ast.Constant, ast.BinOp, ast.UnaryOp)) else None
            if folded is not None:
                if abs(folded) >= _GATE_LITERAL_FLOOR:
                    out.append(Violation(
                        "gate-through-policy", path, node.lineno,
                        f"literal {folded} inside gate function "
                        f"{fn.name}() — dispatch thresholds live in the "
                        "policy calibration table "
                        "(policy/table.GATE_DEFAULTS + goldens/"
                        "calibration.json); resolve through "
                        "policy.gates.resolve()/gate_value() so a device "
                        "entry can move them and the committed default "
                        "stays the single source"))
                continue
            stack.extend(ast.iter_child_nodes(node))
    return out


register(Rule(
    name="gate-through-policy",
    doc="dispatch-gate functions read thresholds from the policy table, "
        "not re-inlined literals",
    targets=("dryad_tpu/config.py", "dryad_tpu/engine/levelwise.py",
             "dryad_tpu/engine/leafwise_fast.py",
             "dryad_tpu/engine/histogram.py", "dryad_tpu/engine/predict.py",
             "dryad_tpu/serve/server.py", "dryad_tpu/resilience/policy.py"),
    check=_check_gate_literals,
))


# ---------------------------------------------------------------------------
# jit-closure-constant

_MATERIALIZERS = {
    "asarray", "array", "zeros", "ones", "full", "empty", "arange",
    "linspace", "load", "fromfile", "frombuffer", "stack", "concatenate",
    "tile", "device_put",
    # host RNG draws are dataset-scale arrays too
    "normal", "uniform", "integers", "random", "standard_normal",
    "permutation", "choice",
}
_ARRAY_ROOTS = {"np", "numpy", "jnp", "jax", "rng"}


def _is_materializer(call: ast.Call) -> bool:
    name = dotted(call.func)
    if not name or "." not in name:
        return False
    root, leaf = name.split(".", 1)[0], name.rsplit(".", 1)[-1]
    return leaf in _MATERIALIZERS and root in _ARRAY_ROOTS


def _bound_names(fn: ast.AST) -> set[str]:
    """Names bound inside a function body (params, assigns, loops, defs)."""
    bound: set[str] = set()
    if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        a = fn.args
        for arg in (list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
                    + ([a.vararg] if a.vararg else [])
                    + ([a.kwarg] if a.kwarg else [])):
            bound.add(arg.arg)
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                             ast.NamedExpr)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Name):
                        bound.add(sub.id)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    bound.add(sub.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            bound.add(node.name)
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for alias in node.names:
                bound.add((alias.asname or alias.name).split(".")[0])
        elif isinstance(node, ast.comprehension):
            for sub in ast.walk(node.target):
                if isinstance(sub, ast.Name):
                    bound.add(sub.id)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            bound.add(node.name)
        elif isinstance(node, ast.withitem) and node.optional_vars:
            for sub in ast.walk(node.optional_vars):
                if isinstance(sub, ast.Name):
                    bound.add(sub.id)
    return bound


def _free_names(fn: ast.AST) -> set[str]:
    bound = _bound_names(fn)
    free: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id not in bound:
                free.add(node.id)
    return free


def _is_jit_expr(node: ast.AST) -> bool:
    """jax.jit / jit, or partial(jax.jit, ...)."""
    name = dotted(node)
    if name in ("jax.jit", "jit"):
        return True
    if isinstance(node, ast.Call):
        fname = dotted(node.func)
        if fname in ("partial", "functools.partial") and node.args:
            return _is_jit_expr(node.args[0])
    return False


def _materializer_assigns(scope: ast.AST) -> dict[str, int]:
    """name -> line for direct assignments from array materializers in this
    scope (nested function bodies excluded — their locals are not this
    scope's bindings)."""
    out: dict[str, int] = {}

    def visit(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Assign) and isinstance(
                    child.value, ast.Call) and _is_materializer(child.value):
                for t in child.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = child.lineno
            visit(child)

    visit(scope)
    return out


def _scope_chain(node: ast.AST, parents: dict) -> list:
    """Enclosing scopes of ``node``, outermost (Module) first, the node
    itself excluded."""
    chain = []
    cur = parents.get(id(node))
    while cur is not None:
        if isinstance(cur, (ast.Module, ast.FunctionDef,
                            ast.AsyncFunctionDef, ast.Lambda)):
            chain.append(cur)
        cur = parents.get(id(cur))
    return list(reversed(chain))


def _check_jit_closures(path, src, tree):
    out = []
    parents: dict[int, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node

    # (jitted function node, jit site line, enclosing scope chain)
    sites: list[tuple] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_expr(d) for d in node.decorator_list):
                sites.append((node, node.lineno, _scope_chain(node, parents)))
        if isinstance(node, ast.Call) and _is_jit_expr(node.func) and node.args:
            target = node.args[0]
            if isinstance(target, ast.Lambda):
                sites.append((target, node.lineno,
                              _scope_chain(node, parents)))
            elif isinstance(target, ast.Name):
                # nearest def with that name whose scope chain is a prefix
                # of the call site's chain (same or enclosing scope)
                call_chain = _scope_chain(node, parents)
                best = None
                for d in ast.walk(tree):
                    if isinstance(d, (ast.FunctionDef, ast.AsyncFunctionDef))\
                            and d.name == target.id:
                        d_chain = _scope_chain(d, parents)
                        if all(any(s is c for c in call_chain)
                               for s in d_chain):
                            if best is None or len(d_chain) > len(best[1]):
                                best = (d, d_chain)
                if best is not None:
                    sites.append((best[0], node.lineno, best[1]))

    seen = set()
    for fn, line, chain in sites:
        key = (id(fn), line)
        if key in seen:
            continue
        seen.add(key)
        free = _free_names(fn)
        for scope in reversed(chain):
            mats = _materializer_assigns(scope)
            for name in sorted(free & set(mats)):
                out.append(Violation(
                    "jit-closure-constant", path, line,
                    f"jitted function closes over {name!r} (materialized at "
                    f"line {mats[name]}) — closed-over arrays become "
                    "program constants and remote compile rejects them "
                    "past ~tens of MB (HTTP 413); pass it as an argument"))
                free.discard(name)   # report the INNERMOST binding only
    return out


register(Rule(
    name="jit-closure-constant",
    doc="no materialized arrays captured by jit closures (HTTP-413 class)",
    targets=("dryad_tpu/**", "bench.py", "scripts/*.py", "__graft_entry__.py"),
    check=_check_jit_closures,
))


# ---------------------------------------------------------------------------
# bench-real-fetch

_FETCHERS = {"float", "int"}
_FETCH_CALLS = {"np.asarray", "numpy.asarray", "np.array", "numpy.array",
                "jax.device_get", "device_get"}


def _call_result_names(fn: ast.AST) -> set[str]:
    """Names bound (anywhere in the function) from a Call result — the
    light dataflow that separates ``float(result)`` (result = prog(...),
    a real device fetch) from ``float(K)`` (a host scalar conversion that
    fetches nothing)."""
    out: set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       (ast.Call,
                                                        ast.Subscript)):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
                elif isinstance(t, ast.Tuple):
                    for el in t.elts:
                        if isinstance(el, ast.Name):
                            out.add(el.id)
    return out


def _has_real_fetch(fn: ast.AST) -> bool:
    from_calls = _call_result_names(fn)
    for call in _calls(fn):
        name = dotted(call.func)
        if name in _FETCH_CALLS:
            return True
        if isinstance(call.func, ast.Name) and call.func.id in _FETCHERS \
                and call.args:
            arg = call.args[0]
            # only count conversions of DEVICE results: a direct call /
            # subscript, or a name assigned from one — float(K) over a
            # host scalar would otherwise silence the rule with no fetch
            if isinstance(arg, (ast.Call, ast.Subscript)):
                return True
            if isinstance(arg, ast.Name) and arg.id in from_calls:
                return True
        if isinstance(call.func, ast.Attribute) and call.func.attr in (
                "item", "tolist"):
            return True
    return False


def _check_bench_fetch(path, src, tree):
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        n_perf = sum(1 for c in _calls(node)
                     if (dotted(c.func) or "").endswith("perf_counter"))
        has_fori = any((dotted(c.func) or "").endswith("fori_loop")
                       for c in _calls(node))
        if n_perf >= 2 and has_fori and not _has_real_fetch(node):
            out.append(Violation(
                "bench-real-fetch", path, node.lineno,
                f"timed fori program in {node.name}() never fetches — "
                "block_until_ready returns instantly on this tunnel and "
                "dispatch is async, so the wall measures nothing; end the "
                "timed region with float(result) or np.asarray"))
    return out


register(Rule(
    name="bench-real-fetch",
    doc="timed fori programs must end in a real host fetch",
    # r13: the harness itself (engine/probes.py) and the profile CLI are
    # in scope — the ONE place the discipline lives must machine-check too
    targets=("bench.py", "scripts/*.py", "dryad_tpu/engine/probes.py",
             "dryad_tpu/__main__.py"),
    check=_check_bench_fetch,
))


# ---------------------------------------------------------------------------
# dead-perturbation

_INT_CASTS = {"int8", "int16", "int32", "int64",
              "uint8", "uint16", "uint32", "uint64"}


def _small_float_const(node: ast.AST) -> bool:
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, float)
            and abs(node.value) < 1.0 and node.value != 0.0)


def _is_small_perturb(binop: ast.AST) -> bool:
    return (isinstance(binop, ast.BinOp)
            and isinstance(binop.op, (ast.Add, ast.Sub))
            and (_small_float_const(binop.left)
                 or _small_float_const(binop.right)))


def _check_dead_perturbation(path, src, tree):
    out = []
    for call in _calls(tree):
        # (x + 0.001).astype(int32-ish)
        if isinstance(call.func, ast.Attribute) and call.func.attr == "astype":
            if _is_small_perturb(call.func.value) and call.args:
                dt = dotted(call.args[0]) or (
                    call.args[0].value if isinstance(call.args[0], ast.Constant)
                    else "")
                if any(i in str(dt) for i in _INT_CASTS):
                    out.append(Violation(
                        "dead-perturbation", path, call.lineno,
                        "fractional perturbation rounded away by an integer "
                        "astype — the input is DEAD and XLA hoists the "
                        "stage out of the timed loop (CLAUDE.md r5b); "
                        "advance the carried scalar by whole units"))
        # jnp.int32(x + 0.001)
        name = dotted(call.func)
        leaf = name.rsplit(".", 1)[-1] if name else ""
        if leaf in _INT_CASTS and call.args and _is_small_perturb(call.args[0]):
            out.append(Violation(
                "dead-perturbation", path, call.lineno,
                "fractional perturbation consumed only through an integer "
                "cast — dead input, the timed stage hoists (CLAUDE.md r5b); "
                "advance by whole units instead"))
    return out


register(Rule(
    name="dead-perturbation",
    doc="perturbations must survive integer rounding to reach the stage",
    # engine/** already covers engine/probes.py; the profile CLI rides too
    targets=("bench.py", "scripts/*.py", "dryad_tpu/engine/**",
             "dryad_tpu/__main__.py"),
    check=_check_dead_perturbation,
))


# ---------------------------------------------------------------------------
# unharnessed-timed-fori (r13)
#
# The timed-fori discipline lives in EXACTLY one place now —
# engine/probes.timed_fori, which adds the runtime liveness proof (two
# perturbation seeds must fetch differing accumulators, so a hoisted or
# rounded-away stage raises instead of measuring 2x fast).  A bench or
# profile script that times a hand-rolled lax.fori_loop (>= 1
# perf_counter + a fori_loop call in one function) has forked the
# discipline again and bypassed the proof.  The archived r3-r5
# ``exp_*`` one-shot experiment records predate the harness and are kept
# verbatim for provenance, so the rule scopes to the LIVING measurement
# surfaces: bench.py and the maintained profile_*/bench_*/smoke_*
# scripts.

def _check_unharnessed_fori(path, src, tree):
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        has_fori = any((dotted(c.func) or "").endswith("fori_loop")
                       for c in _calls(node))
        times = any((dotted(c.func) or "").endswith("perf_counter")
                    for c in _calls(node))
        if has_fori and times:
            out.append(Violation(
                "unharnessed-timed-fori", path, node.lineno,
                f"{node.name}() times a hand-rolled lax.fori_loop — the "
                "timed-fori discipline lives in engine/probes.timed_fori "
                "(runtime liveness proof included); route the measurement "
                "through the harness instead of re-copying it"))
    return out


register(Rule(
    name="unharnessed-timed-fori",
    doc="bench/profile scripts time fori programs only through "
        "engine/probes.timed_fori (the liveness-proven harness)",
    targets=("bench.py", "scripts/profile_*.py", "scripts/bench_*.py",
             "scripts/smoke_*.py"),
    check=_check_unharnessed_fori,
))


# ---------------------------------------------------------------------------
# introspect-compile-only (r12)
#
# Compiled-program introspection (lowered cost_analysis, AOT compile +
# memory_analysis) is measured work: a lower() re-traces the program and
# an AOT compile() pays a FULL backend compile (verified on this jax: AOT
# does not share the jit executable cache).  Those calls are legal ONLY
# inside engine/introspect.py — the whitelisted compile-boundary module,
# which memoizes per program key — and NEVER inside a loop body or a
# function traced by fori_loop/scan (where they would become a
# per-iteration host sync, the exact class CLAUDE.md's never-fetch rule
# bans).  introspect.capture() itself is memoized and loop-safe on the
# HOST side, but must not appear in a traced body either.

_INTROSPECT_PATH = "dryad_tpu/engine/introspect.py"
_INTROSPECT_ATTRS = {"cost_analysis", "memory_analysis"}


def _is_aot_compile(call: ast.Call) -> bool:
    """``<expr>.compile()`` with no arguments — the AOT form; re.compile
    and friends always take the pattern/source argument."""
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr == "compile"
            and not call.args and not call.keywords)


def _traced_body_fns(tree: ast.AST) -> list:
    """Function nodes passed to lax loop combinators — their bodies are
    TRACED per loop trip, so host-side introspection inside them is a
    per-iteration sync (or a trace error) by construction."""
    names: set[str] = set()
    fns: list = []
    for call in _calls(tree):
        nm = dotted(call.func) or ""
        if nm.rsplit(".", 1)[-1] in ("fori_loop", "scan", "while_loop"):
            for arg in call.args:
                if isinstance(arg, ast.Lambda):
                    fns.append(arg)
                elif isinstance(arg, ast.Name):
                    names.add(arg.id)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name in names:
            fns.append(node)
    return fns


def _check_introspect_sites(path, src, tree):
    out = []
    in_introspect = path == _INTROSPECT_PATH
    if not in_introspect:
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) \
                    and node.attr in _INTROSPECT_ATTRS:
                out.append(Violation(
                    "introspect-compile-only", path, node.lineno,
                    f".{node.attr} outside engine/introspect.py — compiled-"
                    "program introspection re-traces (and for memory, "
                    "recompiles); only the memoized compile-boundary "
                    "module may pay that"))
            if isinstance(node, ast.Call) and _is_aot_compile(node):
                out.append(Violation(
                    "introspect-compile-only", path, node.lineno,
                    "zero-arg .compile() outside engine/introspect.py — "
                    "AOT compile does NOT share the jit executable cache "
                    "(measured, r12): this pays a full second backend "
                    "compile; route introspection through "
                    "introspect.capture"))
    # traced fori/scan bodies may never introspect, ANYWHERE (and inside
    # introspect.py itself the expensive calls stay out of host loops too)
    hot_regions: list = list(_traced_body_fns(tree))
    if in_introspect:
        hot_regions += [n for n in ast.walk(tree)
                        if isinstance(n, (ast.For, ast.While))]
    for region in hot_regions:
        for call in _calls(region):
            nm = dotted(call.func) or ""
            leaf = nm.rsplit(".", 1)[-1]
            bad = (leaf in _INTROSPECT_ATTRS or _is_aot_compile(call)
                   or nm.endswith("introspect.capture"))
            if bad:
                out.append(Violation(
                    "introspect-compile-only", path, call.lineno,
                    f"{nm or leaf}(...) inside a loop/traced body — the "
                    "tripwire must never become a per-iteration host "
                    "sync; introspect at the compile boundary only"))
    return out


register(Rule(
    name="introspect-compile-only",
    doc="program introspection lives in engine/introspect.py, never in "
        "loops or traced bodies",
    targets=("dryad_tpu/engine/**", "dryad_tpu/serve/**",
             "dryad_tpu/resilience/**", "dryad_tpu/obs/**"),
    check=_check_introspect_sites,
))
