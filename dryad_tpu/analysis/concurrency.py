"""dryadlint layer 3 (static half): concurrency contracts for the
threaded host plane.

Since r8 the host plane has grown a real threaded surface — the fleet
router/supervisor, the serve micro-batcher, the obs registry/watchdog/
exporter, the resilience journal/injector — and its lock discipline was
enforced only by review: the r13/r14 review passes each caught real
races by hand (the batcher stop/start generation race, the injector's
non-atomic check-and-clear, unlocked journal writes, recovery blocking
the monitor thread).  These rules pin that discipline the way layer 1
pins the measured device invariants.  Exit code 6 (see __main__.py)
distinguishes a concurrency-contract violation from ordinary lint.

The conventions the rules enforce:

* **GUARDED_BY declarations.**  A class that owns a lock
  (``self.<x> = threading.Lock()`` in ``__init__``) MUST declare which
  attributes that lock guards — either a class constant
  ``GUARDED_BY = {"_attr": "_lock"}`` (a literal dict) or, for small
  classes, a ``# guarded-by: _lock`` comment on the attribute's
  ``__init__`` assignment line.  Every read/write of a guarded attribute
  outside ``__init__`` must then sit lexically inside a
  ``with self.<lock>:`` block.  Helper methods whose name ends in
  ``_locked`` are the documented called-with-the-lock-held idiom: their
  bodies are exempt, and in exchange every CALL of a ``self.*_locked``
  method must itself sit under a ``with self.<lock>:`` block.
  Benign lock-free fast paths (the double-checked create in
  ``Registry._family``) carry the standard mandatory-reason waiver, so
  every exception is on the record.

* **No blocking under a lock.**  Inside any ``with <lock>:`` body
  (anything whose final name component contains "lock") the blocking
  primitives are banned: ``sleep``, thread/process ``join``/``wait``/
  ``communicate``, blocking queue ``get``/``put``, socket/HTTP verbs
  (``request``/``getresponse``/``urlopen``/``connect``/``accept``/
  ``recv``/``sendall``), and calls of constructor-injected user
  callbacks (``self.cb(...)`` where ``__init__`` stored a parameter on
  ``self``).  This is the class the registry-eviction and
  replica-recovery fixes belong to: a lock held across a blocking call
  turns one slow peer into a plane-wide stall.

* **Lock order.**  Every statically visible two-lock nesting (a
  ``with self.<A>:`` region that acquires ``self.<B>`` — directly or
  through intra-class ``self.<method>()`` calls, transitively) must be
  derivable from the committed partial order in
  ``analysis/goldens/lock_order.json``.  A nesting that INVERTS a
  committed edge is the deadlock shape; a new nesting must be committed
  consciously (the goldens diff is the review event, exactly like the
  jaxpr digests).  Re-acquiring a held non-reentrant lock — directly or
  through a self-call — is always a violation.  Cross-OBJECT order
  (e.g. a registry lock taken inside an entry lock) is invisible to a
  lexical scan; the schedule harness (analysis/schedules.py) records
  those orders at runtime and raises on cycles.
"""

from __future__ import annotations

import ast
import json
import re
from typing import Iterable, Optional

from dryad_tpu.analysis.lint import Rule, Violation, register
from dryad_tpu.analysis.rules import dotted

#: the threaded host plane — the packages the schedule harness drills
#: (r20 adds the data plane's chunk prefetcher: the one threaded class
#: outside the serve/fleet stack)
TARGETS = ("dryad_tpu/continual/**", "dryad_tpu/fleet/**",
           "dryad_tpu/serve/**",
           "dryad_tpu/obs/**", "dryad_tpu/resilience/**",
           "dryad_tpu/data/stream_dataset.py")

LOCK_ORDER_GOLDENS = "dryad_tpu/analysis/goldens/lock_order.json"

#: the rules whose violations exit with code 6 instead of 2 (see
#: __main__.py) — the concurrency layer's distinct CI signal
RULE_NAMES = ("guarded-by", "no-blocking-under-lock", "lock-order")

_GUARD_COMMENT_RE = re.compile(r"#\s*guarded-by:\s*(\w+)")
_LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}


# ---------------------------------------------------------------------------
# shared class-shape helpers


def _classes(tree: ast.AST) -> Iterable[ast.ClassDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            yield node


def _methods(cls: ast.ClassDef) -> Iterable[ast.FunctionDef]:
    for node in cls.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _init_of(cls: ast.ClassDef):
    for m in _methods(cls):
        if m.name == "__init__":
            return m
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for ``self.x`` Attribute nodes, else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _lock_attrs(cls: ast.ClassDef) -> dict:
    """Attributes assigned a ``threading.Lock()``/``RLock()`` in
    ``__init__`` -> assignment line."""
    out: dict[str, int] = {}
    init = _init_of(cls)
    if init is None:
        return out
    for node in ast.walk(init):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if dotted(node.value.func) in _LOCK_CTORS:
                for t in node.targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        out[attr] = node.lineno
    return out


def _guarded_by(cls: ast.ClassDef, src: str):
    """The class's guard declaration: ``{attr: lock_attr}`` merged from
    the ``GUARDED_BY`` class constant and ``# guarded-by: <lock>`` field
    comments in ``__init__``; None when the class declares nothing.
    Returns (mapping_or_None, problems) where problems are non-literal
    declarations."""
    mapping: Optional[dict] = None
    problems: list[tuple[int, str]] = []
    for node in cls.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "GUARDED_BY":
                    if not isinstance(node.value, ast.Dict):
                        problems.append((node.lineno,
                                         "GUARDED_BY must be a literal dict"))
                        continue
                    mapping = {} if mapping is None else mapping
                    for k, v in zip(node.value.keys, node.value.values):
                        if (isinstance(k, ast.Constant)
                                and isinstance(v, ast.Constant)):
                            mapping[str(k.value)] = str(v.value)
                        else:
                            problems.append(
                                (node.lineno, "GUARDED_BY keys/values must "
                                              "be string literals"))
    init = _init_of(cls)
    if init is not None:
        lines = src.splitlines()
        for node in ast.walk(init):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                if node.lineno > len(lines):
                    continue
                m = _GUARD_COMMENT_RE.search(lines[node.lineno - 1])
                if not m:
                    continue
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    attr = _self_attr(t)
                    if attr is not None:
                        mapping = {} if mapping is None else mapping
                        mapping[attr] = m.group(1)
    return mapping, problems


def _held_locks_map(fn: ast.AST) -> dict:
    """id(node) -> frozenset of self-lock attribute names lexically held
    at that node (``with self.<lock>:`` ancestry within ``fn``)."""
    held_at: dict[int, frozenset] = {}

    def locks_of(with_node: ast.With) -> frozenset:
        out = set()
        for item in with_node.items:
            attr = _self_attr(item.context_expr)
            if attr is not None:
                out.add(attr)
        return frozenset(out)

    def visit(node: ast.AST, held: frozenset) -> None:
        held_at[id(node)] = held
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                visit(item, held)
            inner = held | locks_of(node)
            for st in node.body:
                visit(st, inner)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    visit(fn, frozenset())
    return held_at


# ---------------------------------------------------------------------------
# guarded-by


def _check_guarded_by(path, src, tree):
    out = []
    for cls in _classes(tree):
        locks = _lock_attrs(cls)
        gb, problems = _guarded_by(cls, src)
        for line, msg in problems:
            out.append(Violation("guarded-by", path, line,
                                 f"{cls.name}: {msg} (the lint reads it "
                                 "statically)"))
        if locks and gb is None:
            out.append(Violation(
                "guarded-by", path, cls.lineno,
                f"class {cls.name} owns a lock "
                f"({', '.join(sorted(locks))}) but declares no GUARDED_BY "
                "map — every threaded class must state which attributes "
                "its lock guards (GUARDED_BY = {\"_attr\": \"_lock\"} or a "
                "'# guarded-by: _lock' field comment)"))
            continue
        if not gb:
            continue
        for attr, lock in sorted(gb.items()):
            if lock not in locks:
                out.append(Violation(
                    "guarded-by", path, cls.lineno,
                    f"{cls.name}.GUARDED_BY guards {attr!r} with "
                    f"{lock!r}, but __init__ assigns no "
                    f"self.{lock} = threading.Lock()"))
        method_names = {m.name for m in _methods(cls)}
        seen: set = set()   # one violation per (line, attr): a line like
        # `if self._t is None or not self._t.is_alive():` touches the
        # attr twice but holds ONE waiver slot in the ratchet
        for m in _methods(cls):
            if m.name == "__init__" or m.name.endswith("_locked"):
                continue
            held_at = _held_locks_map(m)
            for node in ast.walk(m):
                attr = _self_attr(node)
                if attr is None or (node.lineno, attr) in seen:
                    continue
                if attr in gb and gb[attr] not in held_at.get(
                        id(node), frozenset()):
                    seen.add((node.lineno, attr))
                    out.append(Violation(
                        "guarded-by", path, node.lineno,
                        f"{cls.name}.{m.name} touches self.{attr} "
                        f"(GUARDED_BY self.{gb[attr]}) outside a "
                        f"`with self.{gb[attr]}:` block — either take the "
                        "lock, move the access into a *_locked helper "
                        "called under it, or waive with the reason the "
                        "lock-free access is benign"))
                    continue
                if (attr.endswith("_locked") and attr in method_names
                        and isinstance(node, ast.Attribute)):
                    # a *_locked helper promises its CALLERS hold the lock
                    if not held_at.get(id(node), frozenset()):
                        out.append(Violation(
                            "guarded-by", path, node.lineno,
                            f"{cls.name}.{m.name} calls self.{attr} "
                            "without holding a class lock — *_locked "
                            "helpers are the called-with-the-lock-held "
                            "idiom; take the lock at the call site"))
    return out


register(Rule(
    name="guarded-by",
    doc="threaded classes declare lock-guarded attributes (GUARDED_BY) "
        "and touch them only under the declared lock",
    targets=TARGETS,
    check=_check_guarded_by,
))


# ---------------------------------------------------------------------------
# no-blocking-under-lock

_BLOCKING_LEAVES = {"sleep", "wait", "communicate", "getresponse", "urlopen",
                    "recv", "recv_into", "accept", "connect", "sendall",
                    "request"}


def _is_lockish(expr: ast.AST) -> bool:
    d = dotted(expr)
    return bool(d) and "lock" in d.rsplit(".", 1)[-1].lower()


def _numeric_const(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and isinstance(node.value,
                                                         (int, float))


def _blocking_reason(call: ast.Call) -> Optional[str]:
    """Why this call is a blocking primitive, or None."""
    name = dotted(call.func) or ""
    leaf = name.rsplit(".", 1)[-1]
    if leaf in _BLOCKING_LEAVES:
        return f"{name or leaf}(...) blocks"
    kwnames = {k.arg for k in call.keywords}
    if leaf == "join" and isinstance(call.func, ast.Attribute):
        if isinstance(call.func.value, ast.Constant):
            return None         # "sep".join(...) — string join
        if (not call.args and not call.keywords) or "timeout" in kwnames \
                or (len(call.args) == 1 and _numeric_const(call.args[0])):
            return "thread join blocks"
        return None
    if leaf == "get" and isinstance(call.func, ast.Attribute):
        # blocking queue get: zero positional args, or timeout/block kw;
        # dict.get(key[, default]) always passes the key positionally
        if not call.args or kwnames & {"timeout", "block"}:
            return "blocking queue get"
        return None
    if leaf == "put" and isinstance(call.func, ast.Attribute):
        return "bounded-queue put can block (use put_nowait or move it " \
               "outside the lock)"
    return None


def _callback_attrs(cls: ast.ClassDef) -> set:
    """Constructor-injected callables: ``self.X = P`` in __init__ where P
    is a bare parameter name — calling one under a lock hands the lock's
    critical section to arbitrary user code."""
    init = _init_of(cls)
    if init is None:
        return set()
    params = {a.arg for a in (list(init.args.posonlyargs) + list(init.args.args)
                              + list(init.args.kwonlyargs))}
    out = set()
    for node in ast.walk(init):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Name) \
                and node.value.id in params:
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None:
                    out.add(attr)
    return out


def _check_no_blocking(path, src, tree):
    out = []
    seen: set = set()

    # class context first, so callback calls are recognizable
    cls_of: dict[int, ast.ClassDef] = {}
    for cls in _classes(tree):
        for node in ast.walk(cls):
            cls_of.setdefault(id(node), cls)
    callbacks = {cls.name: _callback_attrs(cls) for cls in _classes(tree)}

    for with_node in ast.walk(tree):
        if not isinstance(with_node, (ast.With, ast.AsyncWith)):
            continue
        if not any(_is_lockish(item.context_expr)
                   for item in with_node.items):
            continue
        lock_repr = ", ".join(dotted(item.context_expr) or "?"
                              for item in with_node.items
                              if _is_lockish(item.context_expr))
        for st in with_node.body:
            for node in ast.walk(st):
                if not isinstance(node, ast.Call):
                    continue
                reason = _blocking_reason(node)
                cls = cls_of.get(id(with_node))
                if reason is None and cls is not None:
                    attr = _self_attr(node.func)
                    if attr in callbacks.get(cls.name, ()):
                        reason = (f"self.{attr} is a constructor-injected "
                                  "user callback — invoking it hands the "
                                  "critical section to arbitrary code")
                if reason is None:
                    continue
                key = (node.lineno, dotted(node.func) or "")
                if key in seen:
                    continue
                seen.add(key)
                out.append(Violation(
                    "no-blocking-under-lock", path, node.lineno,
                    f"{reason} inside `with {lock_repr}:` — a lock held "
                    "across a blocking call turns one slow peer into a "
                    "plane-wide stall (the registry-eviction / "
                    "replica-recovery fix class); do the blocking work "
                    "outside the lock"))
    return out


register(Rule(
    name="no-blocking-under-lock",
    doc="no sleep/join/wait/socket/queue-blocking or user-callback calls "
        "inside a `with <lock>:` body",
    targets=TARGETS,
    check=_check_no_blocking,
))


# ---------------------------------------------------------------------------
# lock-order


def _direct_lock_withs(fn: ast.AST, locks: dict) -> list:
    """(with_node, frozenset(lock_attrs)) for every ``with self.<lock>``
    in ``fn`` whose lock attr is a declared class lock."""
    out = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = frozenset(a for item in node.items
                                 for a in [_self_attr(item.context_expr)]
                                 if a in locks)
            if acquired:
                out.append((node, acquired))
    return out


def _self_calls(node: ast.AST, method_names: set) -> list:
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            attr = _self_attr(sub.func)
            if attr in method_names:
                out.append((sub, attr))
    return out


def _closure_locks(cls: ast.ClassDef, locks: dict) -> dict:
    """method name -> set of class locks the method may acquire, through
    any chain of intra-class self-calls (fixpoint; cycle-safe)."""
    methods = {m.name: m for m in _methods(cls)}
    direct = {name: {a for _, acq in _direct_lock_withs(m, locks)
                     for a in acq}
              for name, m in methods.items()}
    calls = {name: {c for _, c in _self_calls(m, set(methods))}
             for name, m in methods.items()}
    closure = {name: set(direct[name]) for name in methods}
    changed = True
    while changed:
        changed = False
        for name in methods:
            for callee in calls[name]:
                add = closure[callee] - closure[name]
                if add:
                    closure[name] |= add
                    changed = True
    return closure


def _observed_edges(path, tree):
    """[(outer_id, inner_id, line, detail)] for statically visible
    nestings, plus [(line, message)] for held-lock re-acquisitions."""
    edges = []
    reacquired = []
    for cls in _classes(tree):
        locks = _lock_attrs(cls)
        if not locks:
            continue
        methods = {m.name for m in _methods(cls)}
        closure = _closure_locks(cls, locks)

        def qual(attr: str) -> str:
            return f"{cls.name}.{attr}"

        for m in _methods(cls):
            held_at = _held_locks_map(m)
            for with_node, acquired in _direct_lock_withs(m, locks):
                held = held_at.get(id(with_node), frozenset()) & set(locks)
                for a in acquired:
                    if a in held:
                        reacquired.append((
                            with_node.lineno,
                            f"{cls.name}.{m.name} re-acquires held "
                            f"non-reentrant lock self.{a}"))
                    for h in held:
                        if h != a:
                            edges.append((qual(h), qual(a), with_node.lineno,
                                          f"{cls.name}.{m.name}"))
            for call, callee in _self_calls(m, methods):
                held = held_at.get(id(call), frozenset()) & set(locks)
                if not held:
                    continue
                for a in closure.get(callee, ()):
                    if a in held:
                        reacquired.append((
                            call.lineno,
                            f"{cls.name}.{m.name} holds self.{a} and calls "
                            f"self.{callee}(), which (transitively) "
                            f"acquires self.{a} again — self-deadlock"))
                    else:
                        for h in held:
                            edges.append((qual(h), qual(a), call.lineno,
                                          f"{cls.name}.{m.name} -> "
                                          f"self.{callee}()"))
    return edges, reacquired


def _transitive(pairs) -> set:
    closed = set(pairs)
    changed = True
    while changed:
        changed = False
        for a, b in list(closed):
            for c, d in list(closed):
                if b == c and (a, d) not in closed:
                    closed.add((a, d))
                    changed = True
    return closed


def _committed_order(tree):
    """(allowed transitive closure, error message or None).  A tree that
    carries no goldens of its own (fixture roots in tests) falls back to
    the package's committed file."""
    try:
        try:
            raw = tree.read(LOCK_ORDER_GOLDENS)
        except FileNotFoundError:
            import os

            with open(os.path.join(os.path.dirname(__file__), "goldens",
                                   "lock_order.json")) as f:
                raw = f.read()
        doc = json.loads(raw)
        edges = [tuple(e) for e in doc["edges"]]
    except FileNotFoundError:
        return set(), (f"{LOCK_ORDER_GOLDENS} is missing — commit the "
                       "lock partial order")
    except (ValueError, KeyError, TypeError) as e:
        return set(), f"{LOCK_ORDER_GOLDENS} is malformed: {e!r}"
    closed = _transitive(edges)
    for a, b in closed:
        if (b, a) in closed or a == b:
            return set(), (f"{LOCK_ORDER_GOLDENS} commits a CYCLIC order "
                           f"({a} <-> {b}) — a partial order cannot "
                           "contain both directions")
    return closed, None


def _tree_check_lock_order(sources, tree):
    out = []
    allowed, err = _committed_order(tree)
    first_path = min(sources) if sources else LOCK_ORDER_GOLDENS
    if err is not None:
        return [Violation("lock-order", first_path, 1, err)]
    for rel in sorted(sources):
        _, mod = sources[rel]
        edges, reacquired = _observed_edges(rel, mod)
        for line, msg in reacquired:
            out.append(Violation("lock-order", rel, line, msg))
        seen = set()
        for a, b, line, where in edges:
            if (a, b) in seen:
                continue
            seen.add((a, b))
            if (a, b) in allowed:
                continue
            if (b, a) in allowed:
                out.append(Violation(
                    "lock-order", rel, line,
                    f"{where} acquires {b} while holding {a} — this "
                    f"INVERTS the committed order ({b} before {a}, "
                    f"{LOCK_ORDER_GOLDENS}); the opposite nesting exists "
                    "somewhere, so this is the deadlock shape"))
            else:
                out.append(Violation(
                    "lock-order", rel, line,
                    f"{where} acquires {b} while holding {a}, an order "
                    f"not in the committed partial order — if intentional "
                    f"add [\"{a}\", \"{b}\"] to {LOCK_ORDER_GOLDENS} "
                    "(check the new edge keeps the order acyclic) and "
                    "commit the diff"))
    return out


register(Rule(
    name="lock-order",
    doc="two-lock nestings (direct or via intra-class calls) must follow "
        "the committed partial order in analysis/goldens/lock_order.json",
    targets=TARGETS,
    tree_check=_tree_check_lock_order,
))
