"""CLI: ``python -m dryad_tpu.analysis [--ci|--lint|--audit] [...]``.

Exit codes (scripts/ci.sh keys off them):

    0  everything passed
    2  dryadlint violations (or malformed waivers)
    3  jaxpr audit invariant failure (collective census / _comm_stats
       mismatch, row-sort contract, kernel dtype discipline)
    4  program-digest drift vs the committed goldens
    5  internal error (a rule or an arm crashed — never "pass by crash")

``--update-goldens`` re-traces every arm and rewrites
``dryad_tpu/analysis/goldens/program_digests.json``; run it when a program
change is INTENTIONAL and commit the diff — the review of that diff is
the human half of the fusion-shape tripwire.
"""

from __future__ import annotations

import argparse
import os
import sys


def _force_cpu_env():
    """The audit traces on CPU with 8 virtual devices, exactly like the
    test suite (tests/conftest.py) — set the env BEFORE jax imports."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dryad_tpu.analysis",
        description="dryadlint + jaxpr auditor (see dryad_tpu/analysis)")
    ap.add_argument("--ci", action="store_true",
                    help="run both layers (what scripts/ci.sh runs)")
    ap.add_argument("--lint", action="store_true", help="dryadlint only")
    ap.add_argument("--audit", action="store_true", help="jaxpr audit only")
    ap.add_argument("--update-goldens", action="store_true",
                    help="re-trace arms and rewrite the digest goldens")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--rule", action="append", default=None,
                    help="restrict lint to the named rule(s)")
    ap.add_argument("--arm", action="append", default=None,
                    help="restrict the audit to the named arm(s)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: the package's parent)")
    ap.add_argument("--goldens", default=None,
                    help="goldens path override (tests use a tmp file)")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    root = args.root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    if args.list_rules:
        from dryad_tpu.analysis.lint import registry

        for name, rule in sorted(registry().items()):
            print(f"{name:24s} {rule.doc}")
            print(f"{'':24s}   targets: {', '.join(rule.targets)}")
        return 0

    do_lint = args.ci or args.lint or not (args.audit or args.update_goldens)
    do_audit = args.ci or args.audit or args.update_goldens

    rc = 0
    try:
        if do_lint:
            from dryad_tpu.analysis.lint import run_lint

            report = run_lint(root, rule_names=args.rule)
            for v in report.violations:
                print("VIOLATION", v.format())
            for e in report.errors:
                print("ERROR", e)
            if not args.quiet:
                for v, w in report.waived:
                    print(f"waived   {v.path}:{v.line} [{v.rule}] -- "
                          f"{w.reason}")
            print(report.summary())
            if not report.ok:
                rc = max(rc, 2)

        if do_audit:
            _force_cpu_env()
            from dryad_tpu.analysis.jaxpr_audit import run_audit

            audit = run_audit(arm_names=args.arm,
                              goldens_path=args.goldens,
                              update_goldens=args.update_goldens)
            for arm in audit.arms:
                c = arm.census
                line = (f"arm {arm.name}: psum={c.collectives.get('psum', 0)}"
                        f"/{arm.expected_psums} "
                        f"global_sorts={c.global_row_sorts} "
                        f"local_sorts={c.local_row_sorts} "
                        f"row_gathers={c.row_gathers} "
                        f"digest={arm.digest[:12]}")
                print(line)
                for f in arm.failures:
                    print("  INVARIANT FAIL:", f)
            for d in audit.drift:
                print("DIGEST DRIFT:", d)
            print(audit.summary())
            if args.update_goldens:
                from dryad_tpu.analysis.digests import GOLDENS_PATH

                print("goldens written:", args.goldens or GOLDENS_PATH)
            if not audit.ok:
                rc = max(rc, 3)
            elif not audit.drift_ok:
                rc = max(rc, 4)
    except Exception:
        import traceback

        traceback.print_exc()
        return 5
    return rc


if __name__ == "__main__":
    sys.exit(main())
