"""CLI: ``python -m dryad_tpu.analysis [--ci|--lint|--audit|--concurrency]``.

Exit codes (scripts/ci.sh keys off them):

    0  everything passed
    2  dryadlint violations (or malformed waivers, or the waiver count
       exceeding the committed budget — goldens/waiver_budget.json)
    3  jaxpr audit invariant failure (collective census / _comm_stats
       mismatch, row-sort contract, kernel dtype discipline)
    4  program-digest drift vs the committed goldens
    5  internal error (a rule, an arm, or a drill crashed — never "pass
       by crash")
    6  concurrency-contract violation (r15): a guarded-by /
       no-blocking-under-lock / lock-order lint hit, or a schedule-
       harness drill failure (invariant, deadlock, or lock-order cycle)

``--update-goldens`` re-traces every arm and rewrites
``dryad_tpu/analysis/goldens/program_digests.json``; run it when a program
change is INTENTIONAL and commit the diff — the review of that diff is
the human half of the fusion-shape tripwire.  The lock partial order
(``goldens/lock_order.json``) and the waiver budget
(``goldens/waiver_budget.json``) are edited BY HAND, consciously, in the
same diff as the change that needs them.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

WAIVER_BUDGET_PATH = "dryad_tpu/analysis/goldens/waiver_budget.json"


def _force_cpu_env():
    """The audit traces on CPU with 8 virtual devices, exactly like the
    test suite (tests/conftest.py) — set the env BEFORE jax imports."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def check_waiver_budget(n_waived: int, budget_path: str):
    """(ok, message): the waiver-count ratchet — growing the waiver set
    requires bumping the committed budget in the same diff."""
    try:
        with open(budget_path) as f:
            budget = int(json.load(f)["waivers"])
    except (OSError, ValueError, KeyError) as e:
        return False, f"waiver budget unreadable ({budget_path}): {e!r}"
    if n_waived > budget:
        return False, (
            f"waiver ratchet: {n_waived} waived > budget {budget} "
            f"({budget_path}) — a new waiver is a review event; bump the "
            "budget consciously in the same diff or fix the violation")
    slack = budget - n_waived
    note = (f"waivers {n_waived}/{budget}"
            + (f" (budget can ratchet down by {slack})" if slack else ""))
    return True, note


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m dryad_tpu.analysis",
        description="dryadlint + jaxpr auditor + concurrency harness "
                    "(see dryad_tpu/analysis)")
    ap.add_argument("--ci", action="store_true",
                    help="run all three layers (what scripts/ci.sh runs)")
    ap.add_argument("--lint", action="store_true", help="dryadlint only")
    ap.add_argument("--audit", action="store_true", help="jaxpr audit only")
    ap.add_argument("--concurrency", action="store_true",
                    help="schedule-harness drills only (layer 3 dynamic)")
    ap.add_argument("--update-goldens", action="store_true",
                    help="re-trace arms and rewrite the digest goldens")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--rule", action="append", default=None,
                    help="restrict lint to the named rule(s)")
    ap.add_argument("--arm", action="append", default=None,
                    help="restrict the audit to the named arm(s)")
    ap.add_argument("--drill", action="append", default=None,
                    help="restrict the concurrency drills by name")
    ap.add_argument("--schedules", type=int, default=None,
                    help="schedules per drill (default: each drill's own)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: the package's parent)")
    ap.add_argument("--goldens", default=None,
                    help="goldens path override (tests use a tmp file)")
    ap.add_argument("--waiver-budget", default=None,
                    help="waiver budget path override (tests)")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    root = args.root or os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    if args.list_rules:
        from dryad_tpu.analysis.lint import registry

        for name, rule in sorted(registry().items()):
            print(f"{name:24s} {rule.doc}")
            print(f"{'':24s}   targets: {', '.join(rule.targets)}")
        return 0

    explicit = args.lint or args.audit or args.concurrency \
        or args.update_goldens
    do_lint = args.ci or args.lint or not explicit
    do_audit = args.ci or args.audit or args.update_goldens
    do_conc = args.ci or args.concurrency

    rc = 0
    try:
        if do_lint:
            from dryad_tpu.analysis.concurrency import RULE_NAMES as CONC
            from dryad_tpu.analysis.lint import run_lint

            report = run_lint(root, rule_names=args.rule)
            for v in report.violations:
                print("VIOLATION", v.format())
            for e in report.errors:
                print("ERROR", e)
            if not args.quiet:
                for v, w in report.waived:
                    print(f"waived   {v.path}:{v.line} [{v.rule}] -- "
                          f"{w.reason}")
            budget_path = args.waiver_budget or os.path.join(
                root, WAIVER_BUDGET_PATH)
            if args.waiver_budget is None and not os.path.exists(budget_path):
                # fixture roots (tests) carry no goldens: ratchet against
                # the package's committed budget
                budget_path = os.path.join(os.path.dirname(
                    os.path.abspath(__file__)), "goldens",
                    "waiver_budget.json")
            budget_ok, budget_msg = check_waiver_budget(
                len(report.waived), budget_path)
            if not budget_ok:
                print("ERROR", budget_msg)
            print(report.summary() + " | " + (budget_msg if budget_ok
                                              else "over budget"))
            if any(v.rule in CONC for v in report.violations):
                rc = max(rc, 6)
            if (not report.ok and any(v.rule not in CONC
                                      for v in report.violations)) \
                    or report.errors or not budget_ok:
                rc = max(rc, 2)

        if do_conc:
            from dryad_tpu.analysis.schedules import run_ci_drills

            failures = run_ci_drills(schedules=args.schedules,
                                     quiet=args.quiet, drills=args.drill)
            for f in failures:
                print("CONCURRENCY FAIL", f)
            print(f"schedule harness: {len(failures)} failing drill(s)")
            if failures:
                rc = max(rc, 6)

        if do_audit:
            _force_cpu_env()
            from dryad_tpu.analysis.jaxpr_audit import run_audit

            audit = run_audit(arm_names=args.arm,
                              goldens_path=args.goldens,
                              update_goldens=args.update_goldens)
            for arm in audit.arms:
                c = arm.census
                line = (f"arm {arm.name}: psum={c.collectives.get('psum', 0)}"
                        f"/{arm.expected_psums} "
                        f"global_sorts={c.global_row_sorts} "
                        f"local_sorts={c.local_row_sorts} "
                        f"row_gathers={c.row_gathers} "
                        f"digest={arm.digest[:12]}")
                print(line)
                for f in arm.failures:
                    print("  INVARIANT FAIL:", f)
            for d in audit.drift:
                print("DIGEST DRIFT:", d)
            print(audit.summary())
            if args.update_goldens:
                from dryad_tpu.analysis.digests import GOLDENS_PATH

                print("goldens written:", args.goldens or GOLDENS_PATH)
            if not audit.ok:
                rc = max(rc, 3)
            elif not audit.drift_ok:
                rc = max(rc, 4)
    except Exception:
        import traceback

        traceback.print_exc()
        return 5
    return rc


if __name__ == "__main__":
    sys.exit(main())
