"""Static analysis for dryad-tpu's measured invariants (round 11).

Two layers, one CLI (``python -m dryad_tpu.analysis --ci``):

* **dryadlint** (``lint.py`` + ``rules.py``) — a stdlib-``ast`` rule engine
  over the repo's source tree.  Each rule encodes one of the hand-enforced
  disciplines that used to live in ``scripts/ci.sh`` grep blocks or only
  in CLAUDE.md prose: host-fetch bans in serve/resilience/obs (including
  TRANSITIVE jax-freedom for ``dryad_tpu/obs/``), row-sort/``tile_plan``
  bans in the wired growers, large-array jit-closure constants (the
  HTTP-413 class), and bench-timing hygiene (timed fori programs must end
  in a real host fetch; perturbations that integer-rounding turns into
  dead inputs are flagged).  Violations are waivable per line with::

      # dryadlint: disable=RULE -- reason

  (the reason is mandatory; waivers are counted and reported).

* **jaxpr auditor** (``jaxpr_audit.py`` + ``digests.py``) — traces the
  growers, histogram builders and sharded predict with ABSTRACT inputs on
  CPU (tracing never compiles, so even the Pallas/TPU programs trace
  anywhere) and walks the closed jaxprs: a trip-count-weighted collective
  census cross-checked against ``engine.train._comm_stats`` on every arm,
  an N-row sort/gather census on the wired layout path, u8/u16 tile-dtype
  discipline at kernel boundaries, and canonicalized per-arm program
  digests pinned by committed goldens so fusion-shape drift (the
  argmax-flip class) fails CI instead of surfacing as a mysterious
  cross-arm divergence.

This package is imported by tests and the CLI only — nothing in the
training/serving path depends on it.
"""

from dryad_tpu.analysis.lint import (  # noqa: F401
    LintReport,
    Rule,
    Violation,
    Waiver,
    registry,
    run_lint,
)
