"""Transitive-import analysis over the repo's own sources.

The obs jax-freedom invariant is about what ``import dryad_tpu.obs``
ultimately PULLS IN, not about what strings appear in obs files — a
refactor that makes ``obs/registry.py`` import a helper from, say,
``dryad_tpu/engine/jax_compat.py`` would pass every text grep while
quietly making the "jax-free by lint" package import jax at module load.
This module resolves imports statically (``ast.Import``/``ImportFrom``,
relative levels included), follows edges through dryad_tpu-internal
modules, and reports the full chain that reaches a banned root.

Only MODULE-LEVEL imports count: a function-local import inside an
internal module is a lazy edge that importing the package does not
execute.  (Obs itself is additionally barred from lazy jax imports by the
direct-ban rule in rules.py, so the split cannot be gamed from inside the
package.)
"""

from __future__ import annotations

import ast
from typing import Iterable


def module_name(relpath: str) -> str:
    """'dryad_tpu/obs/spans.py' -> 'dryad_tpu.obs.spans'."""
    mod = relpath[:-3] if relpath.endswith(".py") else relpath
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


def module_path_candidates(mod: str) -> list[str]:
    base = mod.replace(".", "/")
    return [base + ".py", base + "/__init__.py"]


def module_level_imports(tree: ast.Module, mod: str,
                         is_package: bool) -> set[str]:
    """Absolute module names imported at module level (relative resolved
    against ``mod``).  Conditional module-level imports (try/except, if)
    count — they execute at import time on some path."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, ast.Import):
            if _inside_function(tree, node):
                continue
            for alias in node.names:
                out.add(alias.name)
        elif isinstance(node, ast.ImportFrom):
            if _inside_function(tree, node):
                continue
            if node.level == 0:
                base = node.module or ""
            else:
                parts = mod.split(".")
                # a package's own __init__ resolves level-1 against itself
                anchor = parts if is_package else parts[:-1]
                up = node.level - 1
                anchor = anchor[: len(anchor) - up] if up else anchor
                base = ".".join(anchor + ([node.module] if node.module else []))
            if base:
                out.add(base)
                # ``from pkg import sub`` may bind a submodule: record the
                # candidate edges too, resolved later only if they exist
                for alias in node.names:
                    out.add(f"{base}.{alias.name}")
    return out


def _inside_function(tree: ast.Module, target: ast.AST) -> bool:
    """True when ``target`` sits under a function def (lazy import)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(node):
                if sub is target:
                    return True
    return False


def find_banned_chains(entry_files: Iterable[str], tree,
                       banned_roots: tuple,
                       internal_prefix: str = "dryad_tpu") -> list[tuple]:
    """BFS the import graph from ``entry_files`` (repo-relative paths)
    through the tree's own sources; return ``(chain, banned)`` tuples where
    ``chain`` is the module path from an entry to the import site that
    reaches a ``banned_roots`` module.  Edges into modules outside
    ``internal_prefix`` (stdlib, numpy, ...) terminate unless banned."""
    results: list[tuple] = []
    seen: set[str] = set()
    queue: list[tuple[str, tuple]] = []
    for rel in entry_files:
        queue.append((rel, (module_name(rel),)))

    while queue:
        rel, chain = queue.pop(0)
        if rel in seen:
            continue
        seen.add(rel)
        try:
            src = tree.read(rel)
            mod_ast = ast.parse(src, filename=rel)
        except (OSError, SyntaxError):
            continue
        mod = module_name(rel)
        is_pkg = rel.endswith("__init__.py")
        for imp in sorted(module_level_imports(mod_ast, mod, is_pkg)):
            root = imp.split(".")[0]
            if root in banned_roots:
                results.append((chain + (imp,), root))
                continue
            if root != internal_prefix:
                continue
            for cand in module_path_candidates(imp):
                if tree.exists(cand):
                    queue.append((cand, chain + (module_name(cand),)))
                    break
    return results
