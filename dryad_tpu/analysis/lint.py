"""dryadlint core: source tree, waiver parsing, rule registry, runner.

Design constraints that shaped this module:

* Rules must run against EITHER the real repo tree or a patched overlay of
  it (tests seed violations into copies of the real files — the mutation
  check each rule must pass), so all file access goes through
  ``SourceTree``.
* Waivers are per-line and must carry a reason.  A waiver suppresses one
  rule on one line (the line it sits on, or — for long expressions — the
  line directly below it).  Waived violations are still counted and the
  CLI reports the total, so the waiver budget is visible in CI output.
* Everything here is stdlib-only (``ast``, no jax/numpy): the linter must
  run before, and independently of, any accelerator runtime.
"""

from __future__ import annotations

import ast
import fnmatch
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional

# ``# dryadlint: disable=rule-a,rule-b -- reason`` (reason mandatory);
# ``disable-file=`` at any line waives the rule for the WHOLE file
_WAIVER_RE = re.compile(
    r"#\s*dryadlint:\s*(disable|disable-file)=([A-Za-z0-9_,-]+)\s*--\s*(.+?)\s*$")
# a disable marker with NO reason — always an error, never a suppression
_BAD_WAIVER_RE = re.compile(
    r"#\s*dryadlint:\s*(?:disable|disable-file)=([A-Za-z0-9_,-]+)\s*$")


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass(frozen=True)
class Waiver:
    rule: str
    path: str
    line: int
    reason: str


@dataclass
class LintReport:
    violations: list[Violation] = field(default_factory=list)
    waived: list[tuple[Violation, Waiver]] = field(default_factory=list)
    errors: list[str] = field(default_factory=list)   # parse/bad-waiver

    @property
    def ok(self) -> bool:
        return not self.violations and not self.errors

    def summary(self) -> str:
        return (f"dryadlint: {len(self.violations)} violation(s), "
                f"{len(self.waived)} waived, {len(self.errors)} error(s)")


class SourceTree:
    """Read-only view of the repo's Python sources, with optional overrides.

    ``overrides`` maps repo-relative paths to replacement source text —
    the mutation tests patch one file in memory and re-run a rule without
    touching disk.  An override for a path that does not exist on disk
    adds a virtual file (fixture trees).
    """

    def __init__(self, root: str, overrides: Optional[dict] = None):
        self.root = os.path.abspath(root)
        self.overrides = dict(overrides or {})

    def read(self, relpath: str) -> str:
        if relpath in self.overrides:
            return self.overrides[relpath]
        with open(os.path.join(self.root, relpath), encoding="utf-8") as f:
            return f.read()

    def exists(self, relpath: str) -> bool:
        return relpath in self.overrides or os.path.exists(
            os.path.join(self.root, relpath))

    def find(self, patterns: Iterable[str]) -> list[str]:
        """Repo-relative python files matching any glob pattern (``**``
        crosses directories).  Overrides participate, disk paths that an
        override shadows are deduped."""
        out: set[str] = set()
        for rel in self._walk_disk():
            if any(_match(rel, p) for p in patterns):
                out.add(rel)
        for rel in self.overrides:
            if any(_match(rel, p) for p in patterns):
                out.add(rel)
        return sorted(out)

    def _walk_disk(self) -> Iterable[str]:
        skip = {"__pycache__", ".git", ".pytest_cache"}
        for dirpath, dirnames, filenames in os.walk(self.root):
            dirnames[:] = [d for d in dirnames if d not in skip]
            for fn in filenames:
                if fn.endswith(".py"):
                    full = os.path.join(dirpath, fn)
                    yield os.path.relpath(full, self.root).replace(os.sep, "/")


def _match(rel: str, pattern: str) -> bool:
    if "**" in pattern:
        # fnmatch's * does not cross "/"; translate ** manually
        rx = re.escape(pattern).replace(r"\*\*", ".*").replace(r"\*", "[^/]*")
        return re.fullmatch(rx, rel) is not None
    return fnmatch.fnmatch(rel, pattern)


@dataclass(frozen=True)
class Rule:
    """One named analysis.  ``check(path, src, tree)`` returns a list of
    Violations for one parsed file; ``targets`` are repo-relative globs;
    ``tree_check(sources, tree)`` (when set) runs ONCE over the whole
    file set instead of per file — rules that need a cross-file view
    (the transitive import analysis) use it.
    """

    name: str
    doc: str
    targets: tuple
    check: Optional[Callable] = None
    tree_check: Optional[Callable] = None


_REGISTRY: dict[str, Rule] = {}


def register(rule: Rule) -> Rule:
    if rule.name in _REGISTRY:
        raise ValueError(f"duplicate rule {rule.name!r}")
    _REGISTRY[rule.name] = rule
    return rule


def registry() -> dict[str, Rule]:
    # rules.py / concurrency.py register on import; keep the imports here
    # so ``registry()`` is always complete regardless of import order
    from dryad_tpu.analysis import concurrency as _concurrency  # noqa: F401
    from dryad_tpu.analysis import rules as _rules  # noqa: F401

    return dict(_REGISTRY)


def parse_waivers(path: str, src: str, report: LintReport) -> tuple:
    """(line -> {rule: Waiver}, {rule: Waiver} file-wide).  A line waiver
    covers its own line and the next line (so a comment line can waive the
    long expression under it); ``disable-file`` covers the whole file."""
    out: dict[int, dict[str, Waiver]] = {}
    filewide: dict[str, Waiver] = {}
    for i, text in enumerate(src.splitlines(), start=1):
        bad = _BAD_WAIVER_RE.search(text)
        if bad and not _WAIVER_RE.search(text):
            report.errors.append(
                f"{path}:{i}: dryadlint waiver for {bad.group(1)!r} has no "
                f"'-- reason' (the reason is mandatory)")
            continue
        m = _WAIVER_RE.search(text)
        if not m:
            continue
        for rule in m.group(2).split(","):
            w = Waiver(rule.strip(), path, i, m.group(3))
            if m.group(1) == "disable-file":
                filewide[w.rule] = w
            else:
                for covered in (i, i + 1):
                    out.setdefault(covered, {})[w.rule] = w
    return out, filewide


def run_lint(root: str, rule_names: Optional[Iterable[str]] = None,
             overrides: Optional[dict] = None) -> LintReport:
    """Run the registered rules over the tree rooted at ``root``."""
    tree = SourceTree(root, overrides)
    rules = registry()
    if rule_names is not None:
        unknown = set(rule_names) - set(rules)
        if unknown:
            raise ValueError(f"unknown rule(s): {sorted(unknown)}")
        rules = {k: rules[k] for k in rule_names}

    report = LintReport()
    parsed: dict[str, tuple] = {}

    def get_parsed(rel: str):
        if rel not in parsed:
            src = tree.read(rel)
            try:
                mod = ast.parse(src, filename=rel)
            except SyntaxError as e:
                report.errors.append(f"{rel}: syntax error: {e}")
                mod = None
            parsed[rel] = (src, mod, parse_waivers(rel, src, report))
        return parsed[rel]

    for rule in rules.values():
        files = tree.find(rule.targets)
        raw: list[Violation] = []
        if rule.tree_check is not None:
            sources = {}
            for rel in files:
                src, mod, _ = get_parsed(rel)
                if mod is not None:
                    sources[rel] = (src, mod)
            raw.extend(rule.tree_check(sources, tree))
        if rule.check is not None:
            for rel in files:
                src, mod, _ = get_parsed(rel)
                if mod is None:
                    continue
                raw.extend(rule.check(rel, src, mod))
        for v in raw:
            _, _, (waivers, filewide) = get_parsed(v.path) if tree.exists(
                v.path) else ("", None, ({}, {}))
            w = waivers.get(v.line, {}).get(v.rule) or filewide.get(v.rule)
            if w is not None:
                report.waived.append((v, w))
            else:
                report.violations.append(v)

    report.violations.sort(key=lambda v: (v.path, v.line, v.rule))
    return report
