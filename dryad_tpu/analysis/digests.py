"""Canonical program digests for traced arms, plus the goldens store.

Why digest jaxprs at all: the repo's bitterest divergence class is
FUSION-SHAPE drift — "any pass that replaces another must run the SAME
program on every path, or 1-shard vs N-shard near-tie argmaxes flip"
(CLAUDE.md lowering facts; the roots_sharded and chunked-dispatch
incidents).  The program a near-tie depends on is the traced IR, so a
canonical digest of each arm's closed jaxpr pins it: an innocent-looking
refactor that changes the traced program for ONE arm but not its peers
fails CI with a digest diff instead of surfacing months later as a
mysterious cross-arm parity flake.

The digest is STRUCTURAL, not textual: primitive names, abstract values,
and a cleaned param representation are hashed in program order.  The
pretty-printer's cosmetics (var naming, whitespace, source locations that
``name_and_src_info`` embeds) never reach the hash — a pure line move in
pallas_hist.py must not churn digests — and neither do runtime object
addresses or hash-seed-dependent set orderings.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from typing import Optional

GOLDENS_PATH = os.path.join(os.path.dirname(__file__), "goldens",
                            "program_digests.json")

_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")
_SRC_RE = re.compile(r" at [^\s()\[\]{}]+:\d+")
_PATH_RE = re.compile(r"(/[\w.\-]+)+/dryad_tpu/")


def _clean(text: str) -> str:
    text = _ADDR_RE.sub("0xADDR", text)
    text = _SRC_RE.sub("", text)
    text = _PATH_RE.sub("dryad_tpu/", text)
    return text


def _param_repr(value) -> str:
    """Deterministic repr for an eqn param: sets sorted (their iteration
    order is hash-seed dependent), addresses and source lines stripped,
    callables reduced to their qualname."""
    if isinstance(value, (frozenset, set)):
        return "{" + ",".join(sorted(_param_repr(v) for v in value)) + "}"
    if isinstance(value, dict):
        return "{" + ",".join(
            f"{_param_repr(k)}:{_param_repr(v)}"
            for k, v in sorted(value.items(), key=lambda kv: repr(kv[0])))\
            + "}"
    if isinstance(value, (tuple, list)):
        return "(" + ",".join(_param_repr(v) for v in value) + ")"
    if callable(value) and not isinstance(value, type):
        return getattr(value, "__qualname__", getattr(value, "__name__",
                                                      type(value).__name__))
    return _clean(repr(value))


def _is_jaxpr(v) -> bool:
    return hasattr(v, "eqns") and hasattr(v, "invars")


def _as_jaxpr(v):
    # ClosedJaxpr wraps .jaxpr/.consts; plain Jaxpr has .eqns directly
    if hasattr(v, "jaxpr") and _is_jaxpr(getattr(v, "jaxpr")):
        return v.jaxpr, list(getattr(v, "consts", ()))
    if _is_jaxpr(v):
        return v, []
    return None, []


def iter_sub_jaxprs(eqn):
    """(param_name, jaxpr, consts) for every jaxpr-valued param of an eqn
    (tuples of branches included — lax.cond)."""
    for key, value in eqn.params.items():
        candidates = value if isinstance(value, (tuple, list)) else (value,)
        for i, v in enumerate(candidates):
            j, consts = _as_jaxpr(v)
            if j is not None:
                yield (f"{key}[{i}]" if isinstance(value, (tuple, list))
                       else key), j, consts


def canonical_digest(closed_jaxpr) -> str:
    """sha256 over the structural content of a (closed) jaxpr."""
    h = hashlib.sha256()

    def upd(s: str):
        h.update(s.encode())
        h.update(b"\x00")

    def const_sig(c):
        shape = getattr(c, "shape", None)
        dtype = getattr(c, "dtype", None)
        if shape is None:
            return _param_repr(c)
        sig = f"const[{dtype}{tuple(shape)}]"
        try:
            nbytes = getattr(c, "nbytes", 1 << 30)
            if nbytes <= 4096:
                sig += hashlib.sha256(bytes(memoryview(
                    __import__("numpy").ascontiguousarray(c)))).hexdigest()[:8]
        except Exception:
            pass
        return sig

    def walk(jaxpr, consts):
        upd("jaxpr")
        for v in jaxpr.invars:
            upd(str(v.aval))
        for c in consts:
            upd(const_sig(c))
        for eqn in jaxpr.eqns:
            upd(eqn.primitive.name)
            for iv in eqn.invars:
                # Literals carry BOTH .val and .aval — the value is the
                # program content (x*2 vs x*3 must digest differently)
                if hasattr(iv, "val"):
                    upd(f"lit:{_param_repr(iv.val)}:{getattr(iv, 'aval', '')}")
                elif hasattr(iv, "aval"):
                    upd(str(iv.aval))
                else:
                    upd(_param_repr(iv))
            for ov in eqn.outvars:
                upd(str(ov.aval))
            sub_keys = set()
            for key, j, j_consts in iter_sub_jaxprs(eqn):
                sub_keys.add(key.split("[")[0])
                upd(f"sub:{key}")
                walk(j, j_consts)
            for key in sorted(eqn.params):
                if key in sub_keys:
                    continue
                upd(f"{key}={_param_repr(eqn.params[key])}")
        upd("end")

    j, c = _as_jaxpr(closed_jaxpr)
    walk(j, c or list(getattr(closed_jaxpr, "consts", ())))
    return h.hexdigest()[:32]


def load_goldens(path: Optional[str] = None) -> dict:
    path = path or GOLDENS_PATH
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def save_goldens(data: dict, path: Optional[str] = None) -> str:
    path = path or GOLDENS_PATH
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return path
