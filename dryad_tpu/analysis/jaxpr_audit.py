"""Layer 2: the jaxpr auditor — IR invariants checked mechanically.

Traces the growers, histogram builders and sharded predict with ABSTRACT
inputs (``jax.make_jaxpr`` over ``ShapeDtypeStruct``s — nothing compiles,
nothing runs, so the Pallas/TPU programs trace on a CPU-only box) and
walks the closed jaxprs for the invariants the repo documents:

* **Collective census** — the growers' collective plan is per-arm (r16):
  on the fused arm the ONLY collective is the fused grad/hess/count psum
  in the histogram builders; on the feature arm (hist_reduce="feature")
  each level's builder issues one reduce-scatter and each level ONE
  combine all-gather, with the root still on the fused psum — counts of
  all three are cross-checked against ``_comm_stats``.  GOSS adds one
  global sort per iteration, the L1-family leaf renewal one global
  (leaf, residual) sort per tree; sharded predict has ZERO collectives.
  Counts are TRIP-WEIGHTED: ``fori_loop`` with static bounds lowers to
  ``scan`` whose ``length`` param is in the jaxpr, so "one psum per level
  body x 7 levels" counts as 7.  The census is cross-checked against
  ``engine.train._comm_stats`` on every arm — the accounting and the
  traced program must agree or one of them drifted.
* **Row-sort / row-gather census** — sorts and gathers touching row-scale
  operands, distinguished from (L,)-slot bookkeeping by a per-arm row
  threshold.  The wired layout arms must show ZERO row sorts ("nothing on
  the wired path sorts rows", r10); the legacy arm's tile-plan sorts are
  recorded in the goldens so their count is pinned too.
* **Kernel-boundary dtype discipline** — for every ``pallas_call``, the
  dominant integer operand must be u8/u16 (tiles stay u8/u16 end to end;
  the kernel casts in VMEM — 4x tile HBM traffic otherwise, CLAUDE.md
  lowering facts), and each kernel's full input signature is recorded.
* **Program digests** — a canonical structural digest per arm, compared
  against committed goldens (``--update-goldens`` refreshes after an
  INTENTIONAL program change).  This is the fusion-shape tripwire: any
  pass that replaces another must run the SAME program on every path, or
  near-tie argmaxes flip between arms.

Arm configs are intentionally small (trace cost only — shapes never
execute) but chosen so every audited regime is LIVE: the wired layout
gates admit, the legacy deep phase really runs its tile-plan sort, GOSS
and renewal really emit their one global sort.
"""

from __future__ import annotations

import re
from collections import Counter
from dataclasses import dataclass, field
from typing import Callable, Optional

from dryad_tpu.analysis.digests import (
    GOLDENS_PATH,
    canonical_digest,
    iter_sub_jaxprs,
    load_goldens,
    save_goldens,
)

_COLLECTIVES = frozenset({
    "psum", "psum2", "psum_invariant", "all_reduce", "all_gather",
    "all_gather_invariant", "all_to_all", "ppermute", "pbroadcast",
    "reduce_scatter", "pmin", "pmax", "pgather", "axis_index",
})

# mesh width every arm traces against (matches tests/conftest.py's 8 fake
# CPU devices; the CLI exports the same XLA_FLAGS before importing jax)
N_SHARDS = 8


# ---------------------------------------------------------------------------
# census walk

@dataclass
class Census:
    collectives: Counter = field(default_factory=Counter)
    # row-scale sorts OUTSIDE any shard_map body run on the GLOBAL array
    # (a GSPMD collective sort under a mesh — the GOSS quantile / renewal
    # class); sorts INSIDE a shard_map body are shard-LOCAL implementation
    # details of a builder (the XLA segmented pass sorts its shard per
    # level) and are pinned by goldens, not by the collective contract
    global_row_sorts: int = 0
    local_row_sorts: int = 0
    row_gathers: int = 0
    # gathers whose every operand sits BELOW the row threshold — the
    # small-table per-node lookups of the predict traversal (r21).  Gather
    # cost is per-ACCESS on TPU, so the packed node-word arm's whole point
    # is this count: 1 per level vs the legacy structure-of-arrays 7.
    # Trip-weighted like everything else.
    table_gathers: int = 0
    pallas_kernels: dict = field(default_factory=dict)  # name -> set of sigs
    dynamic_loop: bool = False
    branch_mismatch: bool = False

    def scaled(self, k: int) -> "Census":
        out = Census(Counter({p: n * k for p, n in self.collectives.items()}),
                     self.global_row_sorts * k, self.local_row_sorts * k,
                     self.row_gathers * k, self.table_gathers * k,
                     {n: set(s) for n, s in self.pallas_kernels.items()},
                     self.dynamic_loop, self.branch_mismatch)
        return out

    def add(self, other: "Census") -> None:
        self.collectives.update(other.collectives)
        self.global_row_sorts += other.global_row_sorts
        self.local_row_sorts += other.local_row_sorts
        self.row_gathers += other.row_gathers
        self.table_gathers += other.table_gathers
        for name, sigs in other.pallas_kernels.items():
            self.pallas_kernels.setdefault(name, set()).update(sigs)
        self.dynamic_loop |= other.dynamic_loop
        self.branch_mismatch |= other.branch_mismatch

    @property
    def interesting(self) -> bool:
        return (bool(self.collectives) or self.global_row_sorts
                or self.local_row_sorts or self.row_gathers)


def _aval_sig(v) -> str:
    aval = getattr(v, "aval", None)
    if aval is None:
        return "lit"
    return f"{getattr(aval, 'dtype', '?')}{tuple(getattr(aval, 'shape', ()))}"


def _max_rows(eqn) -> int:
    best = 0
    for v in list(eqn.invars) + list(eqn.outvars):
        shape = tuple(getattr(getattr(v, "aval", None), "shape", ()) or ())
        if shape:
            best = max(best, int(shape[0]))
    return best


def census_jaxpr(jaxpr, row_threshold: int,
                 in_shard_map: bool = False) -> Census:
    """Trip-weighted census of one (possibly closed) jaxpr."""
    j = jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr
    out = Census()
    for eqn in j.eqns:
        name = eqn.primitive.name
        if name in _COLLECTIVES:
            out.collectives[name] += 1
        elif name == "sort" and _max_rows(eqn) >= row_threshold:
            if in_shard_map:
                out.local_row_sorts += 1
            else:
                out.global_row_sorts += 1
        elif name == "gather":
            if _max_rows(eqn) >= row_threshold:
                out.row_gathers += 1
            else:
                out.table_gathers += 1
        elif name == "pallas_call":
            kname = getattr(eqn.params.get("name_and_src_info"), "name",
                            None) or "pallas"
            sig = "(" + ",".join(_aval_sig(v) for v in eqn.invars) + ")"
            out.pallas_kernels.setdefault(kname, set()).add(sig)
            continue  # do not descend into kernel bodies
        subs = [(key, sub, consts)
                for key, sub, consts in iter_sub_jaxprs(eqn)]
        sub_in_sm = in_shard_map or name == "shard_map"
        if name == "scan":
            length = int(eqn.params.get("length", 1))
            for _, sub, _ in subs:
                out.add(census_jaxpr(sub, row_threshold,
                                     sub_in_sm).scaled(length))
        elif name == "while":
            inner = Census()
            for _, sub, _ in subs:
                inner.add(census_jaxpr(sub, row_threshold, sub_in_sm))
            inner.dynamic_loop |= inner.interesting
            out.add(inner)
        elif name == "cond":
            branches = [census_jaxpr(sub, row_threshold, sub_in_sm)
                        for _, sub, _ in subs]
            if branches:
                merged = branches[0]
                for b in branches[1:]:
                    if (b.collectives != merged.collectives
                            or b.global_row_sorts != merged.global_row_sorts):
                        merged.branch_mismatch = True
                    merged.collectives = Counter({
                        p: max(merged.collectives.get(p, 0),
                               b.collectives.get(p, 0))
                        for p in set(merged.collectives) | set(b.collectives)})
                    merged.global_row_sorts = max(merged.global_row_sorts,
                                                  b.global_row_sorts)
                    merged.local_row_sorts = max(merged.local_row_sorts,
                                                 b.local_row_sorts)
                    merged.row_gathers = max(merged.row_gathers, b.row_gathers)
                    merged.table_gathers = max(merged.table_gathers,
                                               b.table_gathers)
                    for n, s in b.pallas_kernels.items():
                        merged.pallas_kernels.setdefault(n, set()).update(s)
                    merged.dynamic_loop |= b.dynamic_loop
                    merged.branch_mismatch |= b.branch_mismatch
                out.add(merged)
        else:
            for _, sub, _ in subs:
                out.add(census_jaxpr(sub, row_threshold, sub_in_sm))
    return out


def kernel_dtype_violations(census: Census) -> list[str]:
    """Tiles stay u8/u16 end to end: for every pallas kernel input
    signature, the LARGEST integer operand must be u8/u16 (f32/bf16
    weights and small i32 seg/pos metadata are expected; an i32 operand
    dominating the integer bytes means someone widened the tiles)."""
    bad = []
    for kname, sigs in sorted(census.pallas_kernels.items()):
        for sig in sorted(sigs):
            best_bytes, best_dtype = 0, None
            for m in re.finditer(r"(u?int\d+)\((\d+(?:,\s*\d+)*)?,?\)", sig):
                dtype = m.group(1)
                dims = [int(x) for x in (m.group(2) or "1").split(",")]
                size = {"int8": 1, "uint8": 1, "int16": 2, "uint16": 2,
                        "int32": 4, "uint32": 4, "int64": 8, "uint64": 8}[dtype]
                nbytes = size
                for d in dims:
                    nbytes *= d
                if nbytes > best_bytes:
                    best_bytes, best_dtype = nbytes, dtype
            if best_dtype is not None and best_dtype not in ("uint8",
                                                             "uint16"):
                bad.append(
                    f"kernel {kname}: dominant integer operand is "
                    f"{best_dtype} in {sig} — tiles must stay u8/u16 into "
                    "the kernel (cast in VMEM; CLAUDE.md lowering facts)")
    return bad


# ---------------------------------------------------------------------------
# arms

@dataclass
class Arm:
    name: str
    doc: str
    build: Callable[[], tuple]       # -> (fn, args, meta dict)


def _mesh():
    import jax

    from dryad_tpu.engine.distributed import make_mesh

    if len(jax.devices()) < N_SHARDS:
        raise RuntimeError(
            f"jaxpr audit needs {N_SHARDS} devices "
            "(set XLA_FLAGS=--xla_force_host_platform_device_count=8, "
            "JAX_PLATFORMS=cpu — the CLI does this automatically)")
    return make_mesh(jax.devices()[:N_SHARDS])


def _abstract_train_args(p, N, F, K):
    import jax
    import jax.numpy as jnp

    from dryad_tpu.booster import CAT_WORDS
    from dryad_tpu.engine.train import _empty_out_device

    out = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
        _empty_out_device(K, p.max_nodes, CAT_WORDS))
    sds = jax.ShapeDtypeStruct
    return (out,
            sds((N, K), jnp.float32),    # score
            sds((N, F), jnp.uint8),      # Xb
            sds((N,), jnp.float32),      # y
            sds((N,), jnp.bool_),        # bag
            sds((F,), jnp.bool_),        # fmask
            sds((F,), jnp.bool_))        # is_cat_feat


def _train_arm(params: dict, *, N=2048, F=8, platform="tpu", K=1,
               renewal=False):
    from dryad_tpu.config import make_params
    from dryad_tpu.engine.train import _comm_stats, _shared_roots_ok
    from dryad_tpu.engine.train import audit_iteration_fn

    p = make_params(params).validate()
    mesh = _mesh()
    renew_a = None
    if renewal:
        from dryad_tpu.objectives import renew_alpha

        renew_a = renew_alpha(p, weighted=False)
        assert renew_a is not None, "renewal arm config does not renew"
    B = int(params["max_bins"])
    fn = audit_iteration_fn(p, B, False, mesh, platform, N, K=K,
                            renew_alpha=renew_a)
    comm = _comm_stats(p, F, B, K, N_SHARDS,
                       shared_roots=K > 1 and _shared_roots_ok(p, platform),
                       num_rows=N, padded_rows=N, platform=platform)
    meta = {
        "rows_threshold": N // N_SHARDS,
        "expected_psums": comm["psum_calls_per_iter"],
        "comm": comm,
    }
    return fn, _abstract_train_args(p, N, F, K), meta


def _arm_levelwise_wired():
    return _train_arm(dict(objective="binary", num_trees=1, num_leaves=127,
                           max_depth=7, growth="depthwise", max_bins=32,
                           hist_backend="pallas"),
                      platform="tpu") + ({"expected_row_sorts": 0,
                                          "wired": True},)


def _arm_levelwise_legacy():
    return _train_arm(dict(objective="binary", num_trees=1, num_leaves=127,
                           max_depth=7, growth="depthwise", max_bins=32,
                           hist_backend="pallas", deep_layout="legacy"),
                      platform="tpu") + ({"expected_row_sorts": 0},)


def _arm_leafwise_wired():
    return _train_arm(dict(objective="binary", num_trees=1, num_leaves=31,
                           max_depth=5, growth="leafwise", max_bins=32,
                           hist_backend="pallas"),
                      platform="tpu") + ({"expected_row_sorts": 0,
                                          "wired": True},)


def _arm_levelwise_feature():
    # the SAME wired config as levelwise_wired with the reduce-scatter
    # arm forced on (F=8 is far below the auto gate — explicit "feature"
    # keeps the trace cheap while the collective plan is fully live:
    # root psum + per-level reduce_scatter + per-level combine all_gather)
    return _train_arm(dict(objective="binary", num_trees=1, num_leaves=127,
                           max_depth=7, growth="depthwise", max_bins=32,
                           hist_backend="pallas", hist_reduce="feature"),
                      platform="tpu") + ({"expected_row_sorts": 0,
                                          "wired": True},)


def _arm_leafwise_feature():
    return _train_arm(dict(objective="binary", num_trees=1, num_leaves=31,
                           max_depth=5, growth="leafwise", max_bins=32,
                           hist_backend="pallas", hist_reduce="feature"),
                      platform="tpu") + ({"expected_row_sorts": 0,
                                          "wired": True},)


def _arm_goss():
    return _train_arm(dict(objective="binary", num_trees=1, num_leaves=127,
                           max_depth=7, growth="depthwise", max_bins=32,
                           hist_backend="pallas", boosting="goss",
                           goss_top_rate=0.3, goss_other_rate=0.2),
                      platform="tpu") + ({"expected_row_sorts": 1,
                                          "wired": True},)


def _arm_renewal():
    return _train_arm(dict(objective="l1", num_trees=1, num_leaves=15,
                           max_depth=4, growth="leafwise", max_bins=32),
                      platform="cpu", renewal=True) \
        + ({"expected_row_sorts": 1},)


def _arm_multiclass_shared_roots():
    return _train_arm(dict(objective="multiclass", num_class=3, num_trees=1,
                           num_leaves=15, max_depth=4, growth="depthwise",
                           max_bins=32),
                      platform="cpu", K=3) + ({"expected_row_sorts": 0},)


def _arm_sharded_predict():
    import jax
    import jax.numpy as jnp

    from dryad_tpu.booster import CAT_WORDS
    from dryad_tpu.engine.predict import sharded_accumulate_fn

    mesh = _mesh()
    N, F, M, n_iter, K, depth = 2048, 8, 63, 3, 1, 6
    fn = sharded_accumulate_fn(mesh, depth)
    sds = jax.ShapeDtypeStruct
    trees = {
        "feature": sds((n_iter, K, M), jnp.int32),
        "threshold": sds((n_iter, K, M), jnp.int32),
        "left": sds((n_iter, K, M), jnp.int32),
        "right": sds((n_iter, K, M), jnp.int32),
        "value": sds((n_iter, K, M), jnp.float32),
        "is_cat": sds((n_iter, K, M), jnp.bool_),
        "cat_bitset": sds((n_iter, K, M, CAT_WORDS), jnp.uint32),
        "default_left": sds((n_iter, K, M), jnp.bool_),
    }
    args = (trees, sds((N, F), jnp.uint8), sds((1,), jnp.float32))
    meta = {"rows_threshold": N // N_SHARDS, "expected_psums": 0,
            "comm": {"psum_calls_per_iter": 0}}
    # legacy structure-of-arrays traversal, CAT program: per level the
    # feature/threshold/default_left/is_cat/left/right lookups + the
    # cat_bitset word = 7 small-table gathers — the baseline the packed
    # arm collapses to 1/level.  (The per-iteration value lookup's index
    # operand is N-long after take_along_axis's reshape, so it lands in
    # row_gathers, not here.)
    return fn, args, meta, {"expected_row_sorts": 0,
                            "collective_free": True,
                            "expected_table_gathers": 3 * 6 * 7}


def _arm_packed_predict():
    import jax
    import jax.numpy as jnp

    from dryad_tpu.engine.predict import sharded_accumulate_fn

    mesh = _mesh()
    N, F, M, n_iter, K, depth = 2048, 8, 63, 3, 1, 6
    fn = sharded_accumulate_fn(mesh, depth)
    sds = jax.ShapeDtypeStruct
    # the r21 packed numeric program: node traversal fields live in ONE
    # (M, 2)-uint32 limb table, no cat_bitset key -> statically bitset-free
    trees = {
        "node_word": sds((n_iter, K, M, 2), jnp.uint32),
        "value": sds((n_iter, K, M), jnp.float32),
    }
    args = (trees, sds((N, F), jnp.uint8), sds((1,), jnp.float32))
    meta = {"rows_threshold": N // N_SHARDS, "expected_psums": 0,
            "comm": {"psum_calls_per_iter": 0}}
    # exactly ONE node-word gather per level — the acceptance pin (<= 2
    # small-table gathers/level; the value lookup rides row_gathers)
    return fn, args, meta, {"expected_row_sorts": 0,
                            "collective_free": True,
                            "expected_table_gathers": 3 * 6 * 1}


ARMS: dict[str, Arm] = {
    "levelwise_wired": Arm(
        "levelwise_wired",
        "root-anchored layout levelwise grower (r10 wired path), sharded",
        _arm_levelwise_wired),
    "levelwise_legacy": Arm(
        "levelwise_legacy",
        "plan-based levelwise comparison arm (deep_layout='legacy')",
        _arm_levelwise_legacy),
    "leafwise_wired": Arm(
        "leafwise_wired",
        "layout-wired batched leaf-wise expansion + selection, sharded",
        _arm_leafwise_wired),
    "levelwise_feature": Arm(
        "levelwise_feature",
        "feature-parallel reduction arm: reduce-scatter + combine "
        "all-gather per level, root psum (hist_reduce='feature')",
        _arm_levelwise_feature),
    "leafwise_feature": Arm(
        "leafwise_feature",
        "feature-parallel batched leaf-wise expansion (reduce-scatter + "
        "combine all-gather per expansion level)",
        _arm_leafwise_feature),
    "goss_iteration": Arm(
        "goss_iteration",
        "GOSS boosting iteration: +1 global row sort over the psums",
        _arm_goss),
    "renewal_iteration": Arm(
        "renewal_iteration",
        "L1 leaf renewal: +1 global (leaf, residual) row sort per tree",
        _arm_renewal),
    "multiclass_shared_roots": Arm(
        "multiclass_shared_roots",
        "K=3 shared-plan roots (XLA backend): one fused root psum for all K",
        _arm_multiclass_shared_roots),
    "sharded_predict": Arm(
        "sharded_predict",
        "shard_map predict: zero collectives (per-row traversal)",
        _arm_sharded_predict),
    "packed_predict": Arm(
        "packed_predict",
        "shard_map packed node-word predict: one table gather per level",
        _arm_packed_predict),
}


# ---------------------------------------------------------------------------
# runner

@dataclass
class ArmReport:
    name: str
    digest: str
    census: Census
    expected_psums: int
    failures: list = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.failures

    def payload(self) -> dict:
        return {
            "digest": self.digest,
            "collectives": dict(sorted(self.census.collectives.items())),
            "global_row_sorts": self.census.global_row_sorts,
            "local_row_sorts": self.census.local_row_sorts,
            "row_gathers": self.census.row_gathers,
            "table_gathers": self.census.table_gathers,
            "pallas_kernels": {k: sorted(v) for k, v in
                               sorted(self.census.pallas_kernels.items())},
        }


@dataclass
class AuditReport:
    arms: list = field(default_factory=list)
    drift: list = field(default_factory=list)   # digest/golden mismatches

    @property
    def ok(self) -> bool:
        return all(a.ok for a in self.arms)

    @property
    def drift_ok(self) -> bool:
        return not self.drift

    def summary(self) -> str:
        bad = [a.name for a in self.arms if not a.ok]
        s = (f"jaxpr audit: {len(self.arms)} arm(s), "
             f"{sum(len(a.failures) for a in self.arms)} invariant "
             f"failure(s), {len(self.drift)} digest drift(s)")
        if bad:
            s += f" [failed: {', '.join(bad)}]"
        return s


def trace_arm(name: str) -> ArmReport:
    import jax

    built = ARMS[name].build()
    fn, args, meta, expect = built
    closed = jax.make_jaxpr(fn)(*args)
    census = census_jaxpr(closed, meta["rows_threshold"])
    digest = canonical_digest(closed)
    rep = ArmReport(name, digest, census, meta["expected_psums"])

    psums = census.collectives.get("psum", 0)
    comm = meta.get("comm") or {}
    rs = census.collectives.get("reduce_scatter", 0)
    ag = census.collectives.get("all_gather", 0)
    exp_rs = comm.get("reduce_scatter_calls_per_iter", 0)
    exp_ag = comm.get("all_gather_calls_per_iter", 0)
    allowed = {"psum", "reduce_scatter", "all_gather"}
    if comm.get("hist_reduce") == "feature":
        # the feature arm derives each shard's owned slice/offset from
        # axis_index — communication-free, not a payload
        allowed.add("axis_index")
    others = {k: v for k, v in census.collectives.items()
              if k not in allowed}
    if census.dynamic_loop:
        rep.failures.append(
            "collective/sort inside a dynamic-trip while loop — census "
            "cannot weight it; use static fori bounds")
    if census.branch_mismatch:
        rep.failures.append(
            "cond branches disagree on collective counts — the same-program "
            "rule requires every branch to run the same collective plan")
    if psums != meta["expected_psums"]:
        rep.failures.append(
            f"psum census {psums} != _comm_stats accounting "
            f"{meta['expected_psums']} (comm={meta.get('comm')}) — the "
            "traced program and the observability accounting drifted")
    if (rs, ag) != (exp_rs, exp_ag):
        rep.failures.append(
            f"reduce_scatter/all_gather census ({rs}, {ag}) != _comm_stats "
            f"accounting ({exp_rs}, {exp_ag}) (comm={comm}) — only the "
            "feature arm's per-level reduce-scatter + combine all-gather "
            "may appear, and in exactly the accounted counts")
    if expect.get("collective_free") and census.collectives:
        rep.failures.append(
            f"collectives {dict(census.collectives)} in a collective-free "
            "arm — sharded predict must stay per-row")
    if not expect.get("collective_free") and others:
        rep.failures.append(
            f"unexpected collectives {others} — the per-arm histogram "
            "reduction (fused psum, or feature-arm reduce-scatter + "
            "combine all-gather) + documented global sorts are the "
            "growers' ONLY collectives")
    if "expected_row_sorts" in expect \
            and census.global_row_sorts != expect["expected_row_sorts"]:
        rep.failures.append(
            f"global row-scale sorts {census.global_row_sorts} != expected "
            f"{expect['expected_row_sorts']} (threshold "
            f"{meta['rows_threshold']} rows) — only GOSS (+1/iter) and L1 "
            "renewal (+1/tree) may sort the global rows")
    if "expected_table_gathers" in expect \
            and census.table_gathers != expect["expected_table_gathers"]:
        rep.failures.append(
            f"small-table gathers {census.table_gathers} != expected "
            f"{expect['expected_table_gathers']} — the predict traversal's "
            "per-level lookup budget drifted (packed arm: exactly 1 "
            "node-word gather/level; gather cost is per-ACCESS, so every "
            "extra lookup is a real per-level cost)")
    if expect.get("wired") and census.local_row_sorts:
        rep.failures.append(
            f"{census.local_row_sorts} row-scale sort(s) inside the wired "
            "grower program — nothing on the wired path sorts rows (r10)")
    rep.failures.extend(kernel_dtype_violations(census))
    return rep


def run_audit(arm_names=None, goldens_path: Optional[str] = None,
              update_goldens: bool = False) -> AuditReport:
    report = AuditReport()
    names = list(arm_names or ARMS)
    payloads = {}
    for name in names:
        rep = trace_arm(name)
        report.arms.append(rep)
        payloads[name] = rep.payload()

    goldens_path = goldens_path or GOLDENS_PATH
    if update_goldens:
        import jax

        if not report.ok:
            # never pin a program that fails its own invariants: the next
            # (fixed) trace would "drift" against a known-bad baseline
            report.drift.append(
                "refusing to write goldens: arm invariant failures above "
                "must be fixed first (a golden must pin a sound program)")
            return report
        # merge into the existing store: refreshing a SUBSET of arms
        # (--arm X --update-goldens) must not delete the other arms'
        # committed pins — that would force a full re-baseline and wash
        # out exactly the unreviewed-drift signal the goldens exist for
        merged = load_goldens(goldens_path).get("arms", {})
        merged.update(payloads)
        save_goldens({"jax_version": jax.__version__,
                      "n_shards": N_SHARDS, "arms": merged}, goldens_path)
        return report

    goldens = load_goldens(goldens_path)
    stored = goldens.get("arms", {})
    import jax

    env = {"jax_version": jax.__version__, "n_shards": N_SHARDS}
    pinned = {k: goldens.get(k) for k in env}
    if goldens and pinned != env:
        # an environment change legitimately re-lowers every program —
        # say so instead of blaming 7 arms of phantom fusion drift
        report.drift.append(
            f"goldens were pinned under {pinned}, this environment is "
            f"{env} — re-baseline with --update-goldens (not a code "
            "regression)")
        return report
    for name in names:
        if name not in stored:
            report.drift.append(
                f"{name}: no committed golden — run --update-goldens and "
                "commit the diff")
            continue
        for key in ("digest", "collectives", "global_row_sorts",
                    "local_row_sorts", "row_gathers", "table_gathers",
                    "pallas_kernels"):
            if stored[name].get(key) != payloads[name][key]:
                report.drift.append(
                    f"{name}: {key} drifted from golden "
                    f"({stored[name].get(key)!r} -> {payloads[name][key]!r})"
                    " — if intentional, re-run with --update-goldens and "
                    "commit; if not, the program changed under you "
                    "(fusion-shape / argmax-flip class)")
    return report
