"""Command-line front end (SURVEY.md §5 config/flag system).

    python -m dryad_tpu train   --config params.json --data X.npy --label y.npy \
        [--valid Xv.npy --valid-label yv.npy] [--model out.dryad] \
        [--checkpoint-dir DIR --checkpoint-every N --resume] \
        [--supervise --journal run.jsonl --retry-budget N] \
        [--metrics-port N [--metrics-host H] [--auth-token T]] \
        [--log-jsonl metrics.jsonl] [--backend auto|tpu|cpu] [--quiet]
    python -m dryad_tpu predict --model m.dryad --data X.npy --out preds.npy [--raw]
    python -m dryad_tpu dump    --model m.dryad [--out model.json]
    python -m dryad_tpu profile [--selftest] [--stage NAME ...] [--rows N] \
        [--k K --reps R --slots P] [--out PROFILE.json] [--list]
    python -m dryad_tpu serve   --model m.dryad [--model fraud=m2.dryad ...] \
        [--host H --port P] [--backend auto|tpu|cpu] \
        [--max-batch-rows N --max-wait-ms F] [--pipeline-depth 2] \
        [--sharded auto|on|off] [--device-budget-mb M] [--log-requests] \
        [--auth-token T] [--port-file F]   # F gets 'host port' when ready \
        [--request X.npy --out p.npy]   # one-shot through the full stack
    python -m dryad_tpu fleet   --model m.dryad --replicas N [--port P] \
        [--journal fleet.jsonl --retry-budget N] [--warmup] \
        [--max-inflight N --bulk-max-inflight N] [--model-cap NAME=N] \
        [--auth-token T]   # supervised replica pool + health-routed router \
        [--continual-data fresh.npz [--retrain-trees K --probation-polls N]]
                           # r19: drift_breach -> warm-start retrain ->
                           # probationed rolling publish (+ auto-rollback)
    python -m dryad_tpu retrain --model m.dryad --data fresh.npz --out g1.dryad \
        [--trees K --refit-decay D --supervise] [--job-index J]
                           # the scheduler's warm-start append worker

Data formats: ``.npy`` (dense float matrix), ``.npz`` with keys
``indptr/indices/values/num_features`` (CSR sparse), or ``.csv``
(comma-separated, no header).  Params JSON accepts the same names/aliases as
``dryad.train`` (config.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np


def _load_matrix(path: str):
    """-> dense ndarray, or ('csr', (indptr, indices, values, num_features))."""
    if path.endswith(".npy"):
        return np.load(path)
    if path.endswith(".npz"):
        z = np.load(path)
        if "indptr" in z.files:
            return ("csr", (z["indptr"], z["indices"], z["values"],
                            int(z["num_features"])))
        return z[z.files[0]]
    if path.endswith(".csv"):
        return np.loadtxt(path, delimiter=",", dtype=np.float32, ndmin=2)
    raise SystemExit(f"unsupported data format: {path} (use .npy/.npz/.csv)")


def _load_vector(path: str) -> np.ndarray:
    return np.asarray(_load_matrix(path)).reshape(-1)


def _make_dataset(data_path, label_path, group_path, params, mapper=None):
    import dryad_tpu as dryad

    y = _load_vector(label_path) if label_path else None
    group = _load_vector(group_path).astype(np.int64) if group_path else None
    X = _load_matrix(data_path)
    kw = dict(
        weight=None, group=group,
        categorical_features=params.categorical_features if params else (),
        max_bins=params.max_bins if params else 256,
        mapper=mapper,
    )
    if isinstance(X, tuple) and X[0] == "csr":
        return dryad.Dataset(None, y, csr=X[1], **kw)
    return dryad.Dataset(X, y, **kw)


def cmd_train(args) -> int:
    import dryad_tpu as dryad
    from dryad_tpu.callbacks import JsonlLogger, log_evaluation
    from dryad_tpu.config import Params

    # pure-argument guards FIRST: a mis-flagged invocation must not pay
    # the full dataset load/bin (minutes at 10M rows) before the usage error
    if args.supervise and not args.checkpoint_dir:
        raise SystemExit("--supervise requires --checkpoint-dir "
                         "(resume is the recovery mechanism)")
    if args.supervise and not args.resume:
        # mid-run faults always auto-resume, but continuing a PRIOR
        # invocation's checkpoints must be explicit (--resume), exactly
        # like the unsupervised path — a stale dir under changed
        # params/data would silently yield a mixed model otherwise.
        from dryad_tpu.checkpoint import Checkpointer

        if Checkpointer.has_checkpoints(args.checkpoint_dir):
            raise SystemExit(
                f"--supervise found existing checkpoints in "
                f"{args.checkpoint_dir}; pass --resume to continue "
                "that run, or clear the directory to start fresh")
    if not args.supervise:
        if args.journal:
            raise SystemExit("--journal is the supervised-run journal; "
                             "it requires --supervise")
        if args.retry_budget is not None:
            raise SystemExit("--retry-budget configures the supervised "
                             "fault budget; it requires --supervise")

    params = Params.from_json(args.config) if args.config else dryad.Params()

    # live observability: mount the metrics endpoint BEFORE the (possibly
    # minutes-long) dataset load so /healthz answers for the whole run;
    # with --supervise --journal the journal is tailed into the registry
    # live, so fault/backoff/resume series appear on /stats as they happen
    exporter = tail = None
    # parse the hold up front: a malformed value must fail HERE, not inside
    # the finally block where it would mask a training error (and skip the
    # model save after a completed run)
    try:
        hold = float(os.environ.get("DRYAD_METRICS_HOLD_S", "0") or 0)
    except ValueError:
        raise SystemExit("DRYAD_METRICS_HOLD_S must be a number, got "
                         f"{os.environ['DRYAD_METRICS_HOLD_S']!r}")
    if args.metrics_port is not None:
        from dryad_tpu.obs import JournalTail, start_exporter
        from dryad_tpu.obs.trends import stats_provider

        # the bench trend ledger rides /stats (r12): when the cwd holds a
        # committed BENCH_r*.json history the report appears under
        # "bench_trends"; with no files it serves an empty ok report
        exporter = start_exporter(host=args.metrics_host,
                                  port=args.metrics_port,
                                  auth_token=args.auth_token,
                                  extra_stats=[stats_provider()])
        if not args.quiet:
            print(f"metrics on http://{exporter.host}:{exporter.port}  "
                  "(GET /stats, /metrics, /healthz)")
        if args.journal:
            tail = JournalTail(args.journal).start()

    trace_buf = None
    if args.trace_out:
        # capture the span tree live; the trace is written in the finally
        # below so a faulted run still leaves its timeline behind
        from dryad_tpu.obs import trace_export

        trace_buf = trace_export.enable_tracing()
        # the ring is process-wide: an in-process caller's SECOND train
        # run would otherwise write the first run's spans into its trace
        trace_buf.clear()

    logger = None
    # everything past exporter/tail startup runs under the finally that
    # stops them: an in-process caller (tests, smoke_obs) hitting a bad
    # --data path or a SystemExit validation below must not leak a bound
    # HTTP server and tail thread
    try:
        ds = _make_dataset(args.data, args.label, args.group, params)
        valid_sets = None
        if args.valid:
            if not args.valid_label:
                raise SystemExit("--valid requires --valid-label")
            vds = _make_dataset(args.valid, args.valid_label,
                                args.valid_group, params, mapper=ds.mapper)
            valid_sets = [vds]

        callbacks = []
        if not args.quiet:
            callbacks.append(log_evaluation(period=args.log_period))
        if args.log_jsonl:
            logger = JsonlLogger(args.log_jsonl)
            callbacks.append(logger)

        if args.supervise:
            # resilient long runs: classify tunnel/device faults, degrade
            # chunking, auto-resume from checkpoints (dryad_tpu/resilience);
            # the stale-checkpoint --resume guard already ran up top
            from dryad_tpu.resilience import RetryPolicy, supervise_train

            policy = (RetryPolicy() if args.retry_budget is None
                      else RetryPolicy(retry_budget=args.retry_budget))
            booster = supervise_train(
                params, ds, valid_sets,
                backend=args.backend,
                policy=policy,
                checkpoint_dir=args.checkpoint_dir,
                checkpoint_every=args.checkpoint_every,
                journal=args.journal,
                callbacks=callbacks,
                profile_dir=args.profile_dir,
            )
        else:
            booster = dryad.train(
                params, ds, valid_sets,
                backend=args.backend,
                callbacks=callbacks,
                checkpoint_dir=args.checkpoint_dir,
                checkpoint_every=args.checkpoint_every,
                resume=args.resume,
                profile_dir=args.profile_dir,
            )
    finally:
        if trace_buf is not None:
            from dryad_tpu.obs import trace_export

            try:
                journal_events = ()
                if args.journal and os.path.exists(args.journal):
                    from dryad_tpu.resilience.journal import RunJournal

                    journal_events = RunJournal.read_last_run(args.journal)
                trace_export.write_trace(args.trace_out,
                                         span_events=trace_buf.events(),
                                         journal_events=journal_events)
                if not args.quiet:
                    print(f"wrote Chrome trace -> {args.trace_out}")
            except Exception as e:  # noqa: BLE001 — the trace is best-
                print(f"trace export failed: {e!r}",  # effort; never mask
                      file=sys.stderr)                # the training error
            finally:
                trace_export.disable_tracing()
        if logger is not None:
            logger.close()
        # DRYAD_METRICS_HOLD_S keeps the endpoint up briefly after the run
        # (smokes/tests scrape the final state through it; 0 = no hold)
        if exporter is not None and hold > 0:
            time.sleep(hold)
        if tail is not None:
            tail.stop()
        if exporter is not None:
            exporter.stop()
    if args.model:
        booster.save(args.model)
        if not args.quiet:
            print(f"saved {booster.num_iterations} iterations -> {args.model}")
    return 0


def cmd_profile(args) -> int:
    """Stage-level device profiler (engine/probes.py): liveness-proven
    timed-fori walls for the named hot-path stages, exported as
    ``dryad_stage_ms`` gauges and a stamped PROFILE artifact the trend
    ledger ingests.  ``--selftest`` is the ci.sh gate: the seeded
    dead-perturbation probe MUST be rejected and every shipped probe must
    pass liveness (CPU, seconds)."""
    from dryad_tpu.engine import probes

    if args.list:
        for name, probe in probes.PROBES.items():
            print(f"{name:20s} {probe.doc}")
        return 0
    if args.selftest and (args.calibrate or args.check_calib):
        # the r23 ci.sh gate: seeded CPU table/gates logic, NO probes —
        # default-table parity with the pre-policy constants, exact
        # single-gate perturbation flips, round-trip, derive rules
        from dryad_tpu.policy import calibrate as calib

        return calib.run_selftest(quiet=args.quiet)
    if args.selftest:
        return probes.run_selftest(quiet=args.quiet)
    if args.calibrate or args.check_calib:
        return _profile_calibrate(args)

    names = args.stage or list(probes.PROBES)
    unknown = [n for n in names if n not in probes.PROBES]
    if unknown:
        raise SystemExit(f"unknown stage(s): {unknown} "
                         f"(see --list)")
    results = []
    for name in names:
        r = probes.run_probe(name, rows=args.rows, K=args.k,
                             reps=args.reps, num_slots=args.slots)
        if not args.quiet:
            flag = "  SUSPECT CAPTURE" if (
                r["spread"] > probes.SPREAD_SUSPECT) else ""
            print(f"stage {name:20s} {r['ms']:10.2f} ms  "
                  f"spread {r['spread']:.3f}{flag}")
        results.append(r)

    from dryad_tpu.obs.profiler import export_stages, profile_artifact
    from dryad_tpu.obs.trends import PROFILE_PATTERN, compare, load_history

    export_stages(results)
    from dryad_tpu.policy.device import current_device_kind

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    art = profile_artifact(
        results, device_kind=current_device_kind(), root=root)
    print(json.dumps(art))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(art, f, indent=1)
            f.write("\n")
    if args.check_trend and not args.trend_root:
        raise SystemExit("--check-trend requires --trend-root (the "
                         "directory holding the PROFILE_r*.json history)")
    if args.trend_root:
        history = load_history(args.trend_root, pattern=PROFILE_PATTERN)
        if not history:
            # an empty/typo'd history must not turn a CI gate green
            msg = (f"no loadable PROFILE_r*.json under {args.trend_root!r}"
                   " — nothing to compare")
            if args.check_trend:
                raise SystemExit(msg)
            print(msg, file=sys.stderr)
        else:
            report = compare(history)
            print(json.dumps({"profile_trends": report}))
            if args.check_trend and not report["ok"]:
                return 1
    return 0


def _profile_calibrate(args) -> int:
    """``profile --calibrate``: A/B-sweep the stage probes per gate and
    write the refreshed device-keyed table + the stamped CALIB artifact
    the trend ledger ingests; ``--check-calib`` instead diffs the live
    sweep's gate resolutions against the committed table (exit 1 on
    drift, like ``bench_trend --check``; suspect captures report but
    never fail)."""
    from dryad_tpu.policy import calibrate as calib
    from dryad_tpu.policy import table as ptable
    from dryad_tpu.policy.device import current_device_kind

    kind = current_device_kind()
    if args.check_calib:
        report = calib.check_calib(device_kind=kind, rows=args.rows,
                                   quiet=args.quiet)
        print(json.dumps({"calib_check": report}))
        return 0 if report["ok"] else 1
    devices, artifact = calib.calibrate(device_kind=kind, rows=args.rows,
                                        quiet=args.quiet)
    print(json.dumps(artifact))
    if args.calib_out:
        ptable.save_table(devices, args.calib_out)
        if not args.quiet:
            print(f"calibration table ({len(devices)} device entr"
                  f"{'y' if len(devices) == 1 else 'ies'}) -> "
                  f"{args.calib_out}", file=sys.stderr)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=1)
            f.write("\n")
    return 0


def cmd_predict(args) -> int:
    import dryad_tpu as dryad

    booster = dryad.Booster.load(args.model)
    X = _load_matrix(args.data)
    if isinstance(X, tuple) and X[0] == "csr":
        from dryad_tpu.data.binning import bin_csr

        indptr, indices, values, nf = X[1]
        Xb = bin_csr(indptr, indices, values, nf, booster.mapper)
        preds = booster.predict_binned(Xb, raw_score=args.raw,
                                       backend=args.backend)
    else:
        preds = booster.predict(np.asarray(X, np.float32), raw_score=args.raw,
                                backend=args.backend)
    np.save(args.out, preds)
    print(f"wrote predictions {preds.shape} -> {args.out}")
    return 0


def cmd_dump(args) -> int:
    import dryad_tpu as dryad

    booster = dryad.Booster.load(args.model)
    # --text emits the versioned round-trippable format (Booster.save_text
    # / load_text — bit-identical predict); the default dump_model() JSON
    # is a lighter inspection view without the mapper
    text = (booster.dump_text() if getattr(args, "text", False)
            else json.dumps(booster.dump_model(), indent=2))
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    else:
        print(text)
    return 0


def cmd_serve(args) -> int:
    from dryad_tpu.serve import PredictServer

    if args.request and not args.out:
        raise SystemExit("--request requires --out")
    server = PredictServer(
        backend=args.backend,
        max_batch_rows=args.max_batch_rows,
        max_wait_ms=args.max_wait_ms,
        queue_size=args.queue_size,
        pipeline_depth=args.pipeline_depth,
        sharded={"auto": "auto", "on": True, "off": False}[args.sharded],
        device_budget_bytes=(args.device_budget_mb * (1 << 20)
                             if args.device_budget_mb else None),
        drift="off" if args.drift_window == 0 else "auto",
        drift_window=args.drift_window,
    )
    import os.path

    for spec in args.model:
        # NAME=path registers a routing alias for multi-model co-serving;
        # a spec that exists on disk, or whose left-of-'=' part looks like
        # a path, is always a plain path (model paths may contain '=')
        name, path = None, spec
        if "=" in spec and not os.path.exists(spec):
            cand, _, rest = spec.partition("=")
            if cand and "/" not in cand and "\\" not in cand:
                name, path = cand, rest
        version = server.load_model(path, name=name)
        if not args.quiet:
            alias = f" (name {name!r})" if name else ""
            print(f"loaded {path} -> version {version}{alias}")

    if args.warmup:
        # compile every (version, bucket) program up front AND arm the
        # recompile tripwire: from here on an unexpected compile degrades
        # /healthz instead of silently stalling traffic (obs/tripwire.py)
        touched = server.warmup()
        if not args.quiet:
            print(f"warmed {touched} (version, bucket) programs; "
                  "recompile tripwire armed")

    if args.request:
        # one-shot mode: run a single request through the FULL serving
        # stack (bucketed compiled predict + micro-batcher) and exit —
        # a smoke/deployment check with no long-lived process
        X = _load_matrix(args.request)
        with server:
            if isinstance(X, tuple) and X[0] == "csr":
                from dryad_tpu.data.binning import bin_csr

                indptr, indices, values, nf = X[1]
                entry = server.registry.get()
                Xb = bin_csr(indptr, indices, values, nf, entry.booster.mapper)
                preds = server.predict(Xb, raw_score=args.raw, binned=True)
            else:
                preds = server.predict(np.asarray(X, np.float32),
                                       raw_score=args.raw)
        np.save(args.out, preds)
        if not args.quiet:
            print(f"wrote predictions {preds.shape} -> {args.out}")
            print(json.dumps(server.stats(), indent=1))
        return 0

    from dryad_tpu.resilience.faults import injector_from_env
    from dryad_tpu.serve.http import make_http_server

    # request tracing (r17): install the span ring so /trace serves and
    # per-request stage spans are captured — DRYAD_TRACE=0 opts out (the
    # obs registry disabled also keeps the request path allocation-free)
    if os.environ.get("DRYAD_TRACE", "1") != "0":
        from dryad_tpu.obs.trace_export import enable_tracing

        enable_tracing()

    # replica fault drills (fleet supervisor -> env -> this process):
    # absent/empty env costs nothing; a malformed spec fails startup loudly
    fault_hook = injector_from_env()
    httpd = make_http_server(server, args.host, args.port,
                             verbose=not args.quiet,
                             log_requests=args.log_requests,
                             auth_token=args.auth_token,
                             fault_hook=fault_hook)
    host, port = httpd.server_address[:2]
    if args.port_file:
        # the fleet handshake: replicas bind port 0, so readiness and the
        # chosen port must be announced race-free — write-then-rename so a
        # watcher never reads a half-written file
        tmp = args.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(f"{host} {port}\n")
        os.replace(tmp, args.port_file)
    print(f"dryad serving on http://{host}:{port}  "
          f"(backend={server.backend}; POST /predict, GET /stats)")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        server.stop()
        print(json.dumps(server.stats(), indent=1))
    return 0


def cmd_retrain(args) -> int:
    """Continual-boosting retrain worker (r19): the ONLY jax-importing
    piece of the drift→retrain→publish loop — the scheduler launches one
    of these as a subprocess per job, so a wedged device dies here, not
    in the fleet control plane.

    Loads the served artifact, warm-start APPENDS ``--trees`` new trees
    on the fresh rows (``--data``: an npz with ``X``/``y``, binned in
    the model's frozen bin space), optionally after a ``Booster.refit``
    re-weighting pass, and saves the new generation with a fresh
    reference profile.  ``DRYAD_CONTINUAL_FAULTS`` (e.g.
    ``retrain:1:bad_generation``) is the deterministic drill knob: a
    fired ``bad_generation`` point trains against a covariate-scaled
    copy of the rows — a structurally valid model whose embedded profile
    breaches against live traffic, exactly what a poisoned retrain data
    pipeline would ship (the probation window must catch it)."""
    # every generation ships a drift baseline unless explicitly disabled
    os.environ.setdefault("DRYAD_PROFILE", "1")

    import dryad_tpu as dryad
    from dryad_tpu.resilience.faults import (BAD_GENERATION,
                                             CONTINUAL_FAULTS_ENV,
                                             injector_from_env)

    model = dryad.Booster.load_any(args.model)

    injector = injector_from_env(env_var=CONTINUAL_FAULTS_ENV)
    fault_fired = None
    scale = None
    if injector is not None:
        pt = injector.take("retrain", args.job_index)
        if pt is not None and pt.kind == BAD_GENERATION:
            # the poisoned-pipeline twin: scale the covariates so the
            # generation's fresh profile is built on rows live traffic
            # never resembles
            scale = np.float32(0.25)
            fault_fired = pt.kind

    if os.path.isdir(args.data):
        # chunked corpus: a directory of npz shards (each with X/y, bound
        # by sorted filename) streamed through the model's frozen mapper
        # into an on-disk spill — drift-triggered retrains work on
        # corpora that never fit in RAM as a single npz (Issue 17)
        from dryad_tpu.data.streaming import dataset_from_chunks

        if args.refit_decay:
            raise SystemExit(
                "--refit-decay needs a resident npz corpus (refit rebinning "
                "touches every raw row at once); drop it or pass one npz")
        shards = sorted(
            os.path.join(args.data, f) for f in os.listdir(args.data)
            if f.endswith(".npz"))
        if not shards:
            raise SystemExit(f"--data {args.data!r} holds no .npz shards")
        ys = []
        for s in shards:
            with np.load(s) as z:
                if "X" not in z.files or "y" not in z.files:
                    raise SystemExit(f"shard {s!r} must hold X and y")
                ys.append(np.asarray(z["y"]))
        y = np.concatenate(ys)

        def corpus_chunks():
            for s in shards:
                with np.load(s) as z:
                    Xc = np.asarray(z["X"], np.float32)
                yield Xc if scale is None else Xc * scale

        spill_path = args.out + ".bins"
        ds = dataset_from_chunks(
            corpus_chunks, y, int(y.shape[0]), model.mapper.num_features,
            mapper=model.mapper, spill=spill_path)
    else:
        z = np.load(args.data)
        if "X" not in z.files or "y" not in z.files:
            raise SystemExit(f"--data {args.data!r} must be an npz with X and y")
        X = np.asarray(z["X"], np.float32)
        y = np.asarray(z["y"])
        if scale is not None:
            X = X * scale

        if args.refit_decay:
            # re-weight the OLD trees' leaves toward the fresh rows first,
            # then append — structure is kept, so the frozen bin space and
            # tree geometry still match for the warm start
            model = model.refit(X, y, decay_rate=args.refit_decay)

        ds = dryad.Dataset(X, y, mapper=model.mapper)
    p = model.params.replace(num_trees=args.trees)

    if args.supervise:
        from dryad_tpu.resilience import RetryPolicy, supervise_train

        ckdir = args.checkpoint_dir or (args.out + ".ckpt")
        booster = supervise_train(p, ds, backend=args.backend,
                                  policy=RetryPolicy(),
                                  checkpoint_dir=ckdir,
                                  journal=args.journal,
                                  init_model=model)
    else:
        booster = dryad.train(p, ds, backend=args.backend, init_model=model)

    if getattr(ds, "is_streamed", False):
        # the spill is a training temporary, not part of the generation
        try:
            os.unlink(ds.path)
        except OSError:
            pass

    if args.text:
        booster.save_text(args.out)
    else:
        booster.save(args.out)
    print(json.dumps({
        "retrain": args.model, "out": args.out,
        "trees_before": model.num_iterations,
        "trees_after": booster.num_iterations,
        "job_index": args.job_index,
        "fault": fault_fired,
        "profile": getattr(booster, "profile", None) is not None,
    }))
    return 0


def cmd_fleet(args) -> int:
    """Replicated serving: N serve subprocesses under lifecycle
    supervision (crash/hang detection, budgeted respawn, journal) behind
    the health-routed fleet router (dryad_tpu/fleet)."""
    from dryad_tpu.fleet import (CapacityController, FleetSupervisor,
                                 make_fleet_router, serve_argv)
    from dryad_tpu.fleet.router import main_loop
    from dryad_tpu.obs.drift import parse_psi_budget
    from dryad_tpu.obs.slo import parse_budgets
    from dryad_tpu.resilience.policy import RetryPolicy

    # pure-argument guards FIRST (the cmd_train idiom): continual boosting
    # needs the journal (the scheduler tails drift_breach from it) and
    # STABLE model names — drift verdicts are keyed by registry alias, so
    # a bare-path spec would change label (v1 -> v2) on the first push
    # and orphan its own probation window
    continual_models = {}
    if args.continual_data:
        if not args.journal:
            raise SystemExit("--continual-data requires --journal (the "
                             "retrain scheduler tails drift_breach events "
                             "from the fleet journal)")
        for spec in args.model:
            name, _, path = spec.partition("=")
            if not path or "/" in name or "\\" in name:
                raise SystemExit(
                    f"--continual-data requires NAME=path model specs "
                    f"(got {spec!r}) — generation pushes keep the alias, "
                    "so the drift verdict survives the swap")
            continual_models[name] = path

    # router-side tracing: the merged /trace endpoint needs the router's
    # own span ring (replicas enable theirs in cmd_serve)
    if os.environ.get("DRYAD_TRACE", "1") != "0":
        from dryad_tpu.obs.trace_export import enable_tracing

        enable_tracing()

    model_caps = {}
    for spec in args.model_cap or []:
        name, _, cap = spec.partition("=")
        if not name or not cap.isdigit():
            raise SystemExit(f"--model-cap wants NAME=N, got {spec!r}")
        model_caps[name] = int(cap)

    def make_argv(index: int, port_file: str) -> list:
        return serve_argv(args.model, port_file, backend=args.backend,
                          max_batch_rows=args.max_batch_rows,
                          max_wait_ms=args.max_wait_ms,
                          queue_size=args.queue_size, warmup=args.warmup,
                          drift_window=args.drift_window,
                          auth_token=args.auth_token)

    # elastic bounds (r22): --replicas alone keeps the frozen-pool
    # behavior (min == max == replicas); explicit bounds arm the
    # capacity controller, and the pool starts inside them
    min_replicas = (args.min_replicas if args.min_replicas is not None
                    else args.replicas)
    max_replicas = (args.max_replicas if args.max_replicas is not None
                    else args.replicas)
    if not 1 <= min_replicas <= max_replicas:
        raise SystemExit("need 1 <= --min-replicas <= --max-replicas")
    n_start = min(max(args.replicas, min_replicas), max_replicas)

    policy = (RetryPolicy() if args.retry_budget is None
              else RetryPolicy(retry_budget=args.retry_budget))
    supervisor = FleetSupervisor(
        make_argv, n_start, policy=policy, journal=args.journal,
        probe_interval_s=args.probe_interval,
        startup_timeout_s=args.startup_timeout)
    # a process MANAGER must not die leaving its children running: the
    # default SIGTERM kills python without unwinding, so `kill <fleet>`
    # would orphan every replica (observed).  Route TERM through the
    # KeyboardInterrupt path main_loop already handles, so the finally
    # below terminates the pool.
    import signal

    def _term(signum, frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _term)
    # start() is INSIDE the try: each replica pays a 10-20 s jax import,
    # so a TERM/Ctrl-C during startup must still reach supervisor.stop()
    # (which terminates whatever was already spawned), or the half-built
    # pool leaks serve processes
    scheduler = None
    controller = None
    try:
        supervisor.start()
        httpd = make_fleet_router(
            supervisor, args.host, args.port,
            max_inflight=args.max_inflight,
            bulk_max_inflight=args.bulk_max_inflight,
            model_caps=model_caps or None,
            request_timeout_s=args.request_timeout,
            min_healthy=args.min_healthy,
            auth_token=args.auth_token, verbose=not args.quiet,
            slo_budgets_ms=parse_budgets(args.slo_ms),
            slo_breach_after=args.slo_breach_after,
            drift_budget_psi=parse_psi_budget(args.drift_psi),
            drift_breach_after=args.drift_breach_after)
        host, port = httpd.server_address[:2]
        if max_replicas > min_replicas:
            controller = CapacityController(
                supervisor, httpd.state.capacity_signals,
                min_replicas=min_replicas, max_replicas=max_replicas,
                breach_after=args.scale_breach_after,
                cooldown_up_s=args.scale_cooldown,
                cooldown_down_s=2.0 * args.scale_cooldown).start()
            httpd.state.autoscale = controller
        if not args.quiet:
            urls = {s.name: s.state()["url"]
                    for s in supervisor.slots}
            elastic = (f", elastic {min_replicas}..{max_replicas}"
                       if controller is not None else "")
            print(f"dryad fleet on http://{host}:{port}  "
                  f"({n_start} replicas{elastic}: {urls}; POST /predict, "
                  "POST /models/push, GET /metrics aggregates the pool)")
        if continual_models:
            from dryad_tpu.continual import (JournalTailer,
                                             ProbationPublisher,
                                             RetrainScheduler,
                                             make_http_verdicts,
                                             make_subprocess_launcher,
                                             make_supervisor_push)

            out_dir = args.continual_out or os.path.join(
                os.path.dirname(os.path.abspath(args.journal)), "continual")
            launch = make_subprocess_launcher(
                args.continual_data, out_dir,
                trees=args.retrain_trees, backend=args.retrain_backend,
                timeout_s=args.retrain_timeout,
                refit_decay=args.retrain_refit_decay,
                supervise=args.retrain_supervise)
            publisher = ProbationPublisher(
                make_supervisor_push(supervisor, auth_token=args.auth_token),
                make_http_verdicts(host, port, auth_token=args.auth_token),
                journal=supervisor.journal,
                probation_polls=args.probation_polls,
                poll_interval_s=args.probation_interval)
            scheduler = RetrainScheduler(
                continual_models, launch,
                journal=supervisor.journal, publisher=publisher,
                policy=policy, cooldown_s=args.retrain_cooldown,
                max_concurrent=args.retrain_max_concurrent,
                source=JournalTailer(args.journal)).start()
            if not args.quiet:
                print(f"continual boosting armed: {sorted(continual_models)} "
                      f"-> {out_dir} (drift_breach triggers a warm-start "
                      "retrain; probationed rolling publish + rollback)")
        main_loop(httpd, quiet=args.quiet)
    finally:
        if scheduler is not None:
            scheduler.stop(timeout_s=5.0)
        if controller is not None:
            # signal first with a short join: an in-flight scale-up
            # unblocks when supervisor.stop() below reaps its child
            controller.stop(timeout_s=2.0)
        supervisor.stop()
        if controller is not None:
            controller.stop(timeout_s=5.0)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="dryad_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    t = sub.add_parser("train", help="train a booster")
    t.add_argument("--config", help="params JSON file")
    t.add_argument("--data", required=True)
    t.add_argument("--label", required=True)
    t.add_argument("--group", help="query sizes for ranking")
    t.add_argument("--valid")
    t.add_argument("--valid-label")
    t.add_argument("--valid-group")
    t.add_argument("--model", help="output model path")
    t.add_argument("--backend", default="auto", choices=["auto", "tpu", "cpu"])
    t.add_argument("--checkpoint-dir")
    t.add_argument("--checkpoint-every", type=int, default=10)
    t.add_argument("--resume", action="store_true")
    t.add_argument("--supervise", action="store_true",
                   help="resilient run: classify tunnel/device faults, "
                        "degrade chunking, auto-resume from checkpoints "
                        "(requires --checkpoint-dir)")
    t.add_argument("--journal",
                   help="supervised-run journal JSONL path (with --supervise)")
    t.add_argument("--retry-budget", type=int, default=None,
                   help="supervised-run fault budget before failing closed")
    t.add_argument("--log-jsonl",
                   help="per-iteration metrics JSONL path (under "
                        "--supervise, post-fault segments re-log the "
                        "replayed iterations — identical values; dedupe by "
                        "keeping the highest supervise_attempt per "
                        "iteration)")
    t.add_argument("--profile-dir", help="capture a jax.profiler trace here")
    t.add_argument("--trace-out",
                   help="write a Chrome trace_event JSON (Perfetto-"
                        "loadable) of the run's span tree — plus the "
                        "journal events under --supervise --journal — "
                        "here (obs/trace_export.py)")
    t.add_argument("--log-period", type=int, default=1)
    t.add_argument("--metrics-port", type=int, default=None,
                   help="mount the live observability endpoint on this "
                        "port for the duration of the run (0 = any free "
                        "port; GET /stats, /metrics, /healthz — "
                        "dryad_tpu/obs); with --supervise --journal the "
                        "journal is tailed into the live series")
    t.add_argument("--metrics-host", default="127.0.0.1")
    t.add_argument("--auth-token", default=os.environ.get("DRYAD_AUTH_TOKEN"),
                   help="bearer token for the metrics endpoint (env "
                        "DRYAD_AUTH_TOKEN; /healthz stays open)")
    t.add_argument("--quiet", action="store_true")
    t.set_defaults(fn=cmd_train)

    pf = sub.add_parser("profile",
                        help="stage-level device profiler (timed-fori "
                             "harness with runtime liveness proofs)")
    pf.add_argument("--selftest", action="store_true",
                    help="prove the liveness proof: the seeded dead probe "
                         "must be rejected, every shipped probe must pass "
                         "(the ci.sh gate; CPU, seconds)")
    pf.add_argument("--list", action="store_true",
                    help="print the stage-probe catalog and exit")
    pf.add_argument("--stage", action="append", default=None,
                    help="restrict to the named stage(s); repeatable")
    pf.add_argument("--rows", type=int, default=None,
                    help="probe row count (default: 1M on device, 8192 CPU)")
    pf.add_argument("--k", type=int, default=3,
                    help="dependent iterations inside the timed fori")
    pf.add_argument("--reps", type=int, default=2,
                    help="timed programs per probe (min is the estimator)")
    pf.add_argument("--slots", type=int, default=64,
                    help="segment/slot count P for the per-level stages")
    pf.add_argument("--out", help="also write the stamped PROFILE (or, "
                                  "with --calibrate, CALIB) JSON here")
    pf.add_argument("--calibrate", action="store_true",
                    help="A/B-sweep the dispatch-gate probes and derive a "
                         "refreshed device-keyed policy table (r23; with "
                         "--selftest: the seeded CPU table/gates gate — "
                         "no probes)")
    pf.add_argument("--check-calib", action="store_true",
                    help="diff a live sweep's gate resolutions against the "
                         "committed policy table; exit 1 on drift (spread-"
                         "vetoed, like bench_trend --check)")
    pf.add_argument("--calib-out", default=None,
                    help="with --calibrate: write the refreshed calibration "
                         "table JSON here (committed devices + this one)")
    pf.add_argument("--trend-root", default=None,
                    help="compare against the PROFILE_r*.json history in "
                         "this directory (newest-vs-median, spread veto)")
    pf.add_argument("--check-trend", action="store_true",
                    help="exit 1 on a profile-trend regression verdict")
    pf.add_argument("--quiet", action="store_true")
    pf.set_defaults(fn=cmd_profile)

    pr = sub.add_parser("predict", help="predict with a saved model")
    pr.add_argument("--model", required=True)
    pr.add_argument("--data", required=True)
    pr.add_argument("--out", required=True)
    pr.add_argument("--raw", action="store_true", help="raw scores (no link)")
    pr.add_argument("--backend", default="cpu", choices=["tpu", "cpu"])
    pr.set_defaults(fn=cmd_predict)

    d = sub.add_parser("dump", help="dump model structure as JSON")
    d.add_argument("--model", required=True)
    d.add_argument("--out")
    d.add_argument("--text", action="store_true",
                   help="versioned round-trippable text format "
                        "(Booster.load_text)")
    d.set_defaults(fn=cmd_dump)

    s = sub.add_parser("serve", help="online inference service")
    s.add_argument("--model", required=True, action="append",
                   help="model path (.dryad binary or text dump), or "
                        "NAME=path to register a routing alias; repeat to "
                        "co-serve several models — the last one is active")
    s.add_argument("--backend", default="auto", choices=["auto", "tpu", "cpu"])
    s.add_argument("--host", default="127.0.0.1")
    s.add_argument("--port", type=int, default=8000)
    s.add_argument("--max-batch-rows", type=int, default=4096,
                   help="micro-batch row cap (also the largest predict bucket)")
    s.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="batch coalescing deadline")
    s.add_argument("--queue-size", type=int, default=256,
                   help="bounded request queue (backpressure)")
    s.add_argument("--pipeline-depth", type=int, default=2,
                   help="overlapped dispatch run-ahead (1 = serial loop)")
    s.add_argument("--sharded", default="auto", choices=["auto", "on", "off"],
                   help="shard big predict buckets over the device mesh "
                        "(auto: rows×outputs threshold)")
    s.add_argument("--device-budget-mb", type=int, default=0,
                   help="staged-model memory budget; 0 = unlimited "
                        "(LRU eviction, active version pinned)")
    s.add_argument("--warmup", action="store_true",
                   help="compile every (version, bucket) predict program "
                        "at startup and arm the recompile tripwire "
                        "(unexpected compiles then degrade /healthz)")
    s.add_argument("--drift-window", type=int, default=8192,
                   help="model-drift monitor window (rows of recent "
                        "traffic compared against the model's embedded "
                        "reference profile; 0 disables drift telemetry)")
    s.add_argument("--log-requests", action="store_true",
                   help="structured JSON request log on stderr")
    s.add_argument("--auth-token", default=os.environ.get("DRYAD_AUTH_TOKEN"),
                   help="bearer token required on every endpoint except "
                        "/healthz (env DRYAD_AUTH_TOKEN)")
    s.add_argument("--request", help="one-shot mode: predict this matrix "
                                     "through the serving stack and exit")
    s.add_argument("--out", help="one-shot mode: output .npy path")
    s.add_argument("--raw", action="store_true", help="raw scores (no link)")
    s.add_argument("--port-file",
                   help="write 'host port' here once listening (atomic "
                        "rename) — the fleet supervisor's readiness "
                        "handshake for --port 0 replicas")
    s.add_argument("--quiet", action="store_true")
    s.set_defaults(fn=cmd_serve)

    rt = sub.add_parser("retrain",
                        help="continual-boosting retrain worker: warm-start "
                             "append on fresh rows (the scheduler's "
                             "subprocess; dryad_tpu/continual)")
    rt.add_argument("--model", required=True,
                    help="served artifact to warm-start from (binary or "
                         "text format)")
    rt.add_argument("--data", required=True,
                    help="fresh rows: an .npz with X and y, or a DIRECTORY "
                         "of .npz shards streamed out-of-core (both binned "
                         "through the model's frozen mapper)")
    rt.add_argument("--out", required=True, help="new-generation artifact path")
    rt.add_argument("--trees", type=int, default=20,
                    help="NEW trees to append (0 = a no-op generation, "
                         "predict-identical to --model)")
    rt.add_argument("--backend", default="cpu",
                    choices=["auto", "tpu", "cpu"])
    rt.add_argument("--refit-decay", type=float, default=0.0,
                    help="re-weight the old trees' leaves toward the fresh "
                         "rows first (Booster.refit decay_rate; 0 skips)")
    rt.add_argument("--supervise", action="store_true",
                    help="run the append under resilience.supervise_train "
                         "(fault classes degrade and resume bitwise)")
    rt.add_argument("--checkpoint-dir",
                    help="supervised-run checkpoint dir (default: "
                         "<out>.ckpt)")
    rt.add_argument("--journal",
                    help="supervised-run journal JSONL (with --supervise)")
    rt.add_argument("--job-index", type=int, default=0,
                    help="global retrain-job index — the "
                         "DRYAD_CONTINUAL_FAULTS iteration the injector "
                         "matches against")
    rt.add_argument("--text", action="store_true",
                    help="save the generation in the text format")
    rt.set_defaults(fn=cmd_retrain)

    fl = sub.add_parser("fleet",
                        help="replicated serving: supervised replica pool "
                             "behind a health-routed router (dryad_tpu/fleet)")
    fl.add_argument("--model", required=True, action="append",
                    help="model path or NAME=path alias; repeat to co-serve "
                         "(every replica loads the same set)")
    fl.add_argument("--replicas", type=int, default=2,
                    help="serve subprocesses in the pool (with elastic "
                         "bounds unset this is also min == max: the "
                         "frozen pre-r22 pool)")
    fl.add_argument("--min-replicas", type=int, default=None,
                    help="elastic floor (r22): the capacity loop never "
                         "drains below this many slots (default "
                         "--replicas)")
    fl.add_argument("--max-replicas", type=int, default=None,
                    help="elastic ceiling (r22): the capacity loop never "
                         "grows past this many slots (default "
                         "--replicas; max > min arms the controller)")
    fl.add_argument("--scale-cooldown", type=float, default=60.0,
                    help="seconds after a scale-up before the next one "
                         "(scale-downs wait 2x this) — one breach burst "
                         "buys one replica, not a ramp-to-max")
    fl.add_argument("--scale-breach-after", type=int, default=2,
                    help="consecutive pressure polls (sustained SLO "
                         "breach or admission saturation) before a "
                         "scale-up is admitted")
    fl.add_argument("--backend", default="auto",
                    choices=["auto", "tpu", "cpu"])
    fl.add_argument("--host", default="127.0.0.1")
    fl.add_argument("--port", type=int, default=8000,
                    help="router port (also serves the aggregated /metrics "
                         "and fleet /healthz; replicas bind free ports)")
    fl.add_argument("--max-batch-rows", type=int, default=4096)
    fl.add_argument("--max-wait-ms", type=float, default=2.0)
    fl.add_argument("--queue-size", type=int, default=256)
    fl.add_argument("--warmup", action="store_true",
                    help="each replica compiles its buckets and arms the "
                         "recompile tripwire at startup")
    fl.add_argument("--max-inflight", type=int, default=64,
                    help="fleet admission cap: beyond this every request "
                         "sheds (503)")
    fl.add_argument("--bulk-max-inflight", type=int, default=None,
                    help="bulk requests shed beyond this in-flight count "
                         "(default max-inflight/2) — interactive survives "
                         "overload first")
    fl.add_argument("--model-cap", action="append", default=None,
                    help="NAME=N per-model in-flight admission cap; "
                         "repeatable")
    fl.add_argument("--request-timeout", type=float, default=30.0,
                    help="per-forward timeout; one retry on a different "
                         "healthy replica")
    fl.add_argument("--min-healthy", type=int, default=1,
                    help="fleet /healthz answers 503 below this many "
                         "routable replicas")
    fl.add_argument("--probe-interval", type=float, default=0.25,
                    help="supervisor health-probe cadence (seconds)")
    fl.add_argument("--slo-ms", default="",
                    help="per-priority p99 budgets as "
                         "'interactive=250,bulk=2000' (ms; the defaults) — "
                         "a SUSTAINED breach degrades the router /healthz; "
                         "'off' disables SLO health-gating")
    fl.add_argument("--slo-breach-after", type=int, default=3,
                    help="consecutive over-budget /healthz evaluations "
                         "before the SLO degrades the router")
    fl.add_argument("--drift-psi", default="",
                    help="PSI budget for the model-drift layer (default "
                         "0.2, the 'significant shift' rule; replicas' "
                         "window counts merge exactly, GET /drift "
                         "reports verdicts, a sustained breach journals "
                         "drift_breach + warns in /healthz payloads — "
                         "warn-only; 'off' disables drift reporting)")
    fl.add_argument("--drift-breach-after", type=int, default=2,
                    help="consecutive over-budget drift windows before "
                         "the breach is sustained (journal + warning)")
    fl.add_argument("--drift-window", type=int, default=8192,
                    help="per-replica drift monitor window in rows "
                         "(serve --drift-window; 0 disables the "
                         "replica-side monitors)")
    fl.add_argument("--startup-timeout", type=float, default=120.0,
                    help="per-replica readiness deadline (device replicas "
                         "pay model load + compile here)")
    fl.add_argument("--retry-budget", type=int, default=None,
                    help="per-replica respawns before the slot fails "
                         "closed (resilience.RetryPolicy)")
    fl.add_argument("--journal",
                    help="fleet journal JSONL path (spawn/crash/respawn/"
                         "swap decisions, append-only)")
    fl.add_argument("--auth-token",
                    default=os.environ.get("DRYAD_AUTH_TOKEN"),
                    help="bearer token for router AND replicas "
                         "(/healthz stays open)")
    fl.add_argument("--continual-data", default=None,
                    help="arm continual boosting: fresh rows each drift-"
                         "triggered retrain appends on — an .npz with X/y "
                         "or a directory of .npz shards (streamed out-of-"
                         "core by the retrain worker); requires --journal "
                         "and NAME=path model specs (dryad_tpu/continual)")
    fl.add_argument("--continual-out", default=None,
                    help="generation artifact dir (default: "
                         "<journal dir>/continual)")
    fl.add_argument("--retrain-trees", type=int, default=20,
                    help="NEW trees each generation appends")
    fl.add_argument("--retrain-backend", default="cpu",
                    choices=["auto", "tpu", "cpu"],
                    help="retrain worker backend (cpu keeps retrains off "
                         "the serving devices)")
    fl.add_argument("--retrain-cooldown", type=float, default=300.0,
                    help="per-model seconds between finished retrains — "
                         "the breach debounce")
    fl.add_argument("--retrain-max-concurrent", type=int, default=1,
                    help="fleet-wide in-flight retrain budget")
    fl.add_argument("--retrain-timeout", type=float, default=1800.0,
                    help="retrain subprocess wall deadline (a wedged "
                         "worker is killed, never waited on)")
    fl.add_argument("--retrain-refit-decay", type=float, default=0.0,
                    help="Booster.refit re-weighting before each append "
                         "(0 skips)")
    fl.add_argument("--retrain-supervise", action="store_true",
                    help="run each retrain under "
                         "resilience.supervise_train")
    fl.add_argument("--probation-polls", type=int, default=5,
                    help="drift-verdict polls a pushed generation must "
                         "survive before promotion")
    fl.add_argument("--probation-interval", type=float, default=2.0,
                    help="seconds between probation polls (each poll is a "
                         "fresh replica scrape + gate evaluation)")
    fl.add_argument("--quiet", action="store_true")
    fl.set_defaults(fn=cmd_fleet)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
