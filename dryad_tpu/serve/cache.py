"""Shape-bucketed compiled-predict cache.

jit specializes on array shapes, so every distinct request size would
compile (and through a remote-TPU tunnel, compile *slowly*).  Instead,
batches are padded up to the next power-of-two row bucket and predicted
at the bucket shape; warm traffic then touches a small fixed set of
programs — at most log2(max_bucket / min_bucket) + 1 per model version —
and never recompiles.  Batches larger than ``max_bucket`` are predicted
in ``max_bucket``-row chunks.

Bitwise contract: padding rows (bin 0 everywhere) and chunking cannot
change the real rows' scores.  Tree traversal and fp32 leaf accumulation
are strictly per-row (one scan carry element per row, no cross-row
reduction anywhere in predict), so a padded program computes exactly the
same per-row arithmetic as an unpadded one — the parity is structural,
not approximate, and tests/test_serve.py pins it across bucket
boundaries.

The cache also serves the no-device fallback: with ``backend='cpu'`` the
per-bucket entry wraps the canonical numpy predict instead of a jitted
program.  Bucketing is kept there too so batching behavior, metrics, and
the warmup discipline are identical on both backends.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


def bucket_rows(n: int, min_bucket: int = 8,
                max_bucket: Optional[int] = None) -> int:
    """Smallest power of two >= n, floored at min_bucket, capped at
    max_bucket (itself rounded up to a power of two by the server)."""
    if n < 1:
        raise ValueError("bucket_rows needs n >= 1")
    b = max(int(min_bucket), 1 << (int(n) - 1).bit_length())
    if max_bucket is not None:
        b = min(b, int(max_bucket))
    return b


class CompiledPredictCache:
    """(version, bucket) → prepared predict callable, with hit/compile
    accounting.  ``backend`` is 'jax' (device-resident jitted accumulate)
    or 'cpu' (canonical numpy predict)."""

    def __init__(self, backend: str = "cpu", metrics=None, *,
                 min_bucket: int = 8, max_bucket: int = 4096):
        if backend not in ("jax", "cpu"):
            raise ValueError(f"unknown cache backend {backend!r}")
        self.backend = backend
        self.metrics = metrics
        self.min_bucket = int(min_bucket)
        # cap must be a power of two so chunk remainders re-bucket cleanly
        self.max_bucket = 1 << (int(max_bucket) - 1).bit_length()
        # one prepared callable per VERSION (the callable is shape-
        # agnostic; on the jax path the per-shape specialization lives in
        # jit's own cache) + per-(version, bucket) warmth accounting: the
        # first call at a bucket shape is what triggers an XLA compile
        self._fns: dict[int, object] = {}
        self._warm: set[tuple[int, int]] = set()

    @property
    def num_entries(self) -> int:
        """Warm (version, bucket) pairs — compiled shapes, not closures."""
        return len(self._warm)

    def buckets(self) -> list[int]:
        """Every bucket size this cache can ever produce — the warmup set."""
        out, b = [], self.min_bucket
        while b <= self.max_bucket:
            out.append(b)
            b <<= 1
        return out

    # ---- prediction --------------------------------------------------------
    def predict_raw(self, entry, Xb: np.ndarray) -> np.ndarray:
        """Raw scores (n, K) fp32 for pre-binned rows, through the bucketed
        compiled program; bitwise equal to the direct unpadded predict."""
        n = int(Xb.shape[0])
        K = entry.num_outputs
        if n == 0:
            return np.zeros((0, K), np.float32)
        out = np.empty((n, K), np.float32)
        for start in range(0, n, self.max_bucket):
            chunk = Xb[start:start + self.max_bucket]
            m = int(chunk.shape[0])
            b = bucket_rows(m, self.min_bucket, self.max_bucket)
            fn = self._get(entry, b)
            if m < b:
                pad = np.zeros((b - m,) + chunk.shape[1:], chunk.dtype)
                chunk = np.concatenate([np.ascontiguousarray(chunk), pad])
            out[start:start + m] = fn(chunk)[:m]
        return out

    # ---- entry construction ------------------------------------------------
    def _get(self, entry, bucket: int):
        key = (entry.version, bucket)
        hit = key in self._warm
        if not hit:
            self._warm.add(key)
        if self.metrics is not None:
            self.metrics.record_cache(hit)
        fn = self._fns.get(entry.version)
        if fn is None:
            fn = (self._build_jax(entry) if self.backend == "jax"
                  else self._build_cpu(entry))
            self._fns[entry.version] = fn
        return fn

    def _build_cpu(self, entry):
        from dryad_tpu.cpu.predict import predict_binned_cpu

        booster, num_iteration = entry.booster, entry.num_iteration

        def fn(Xp):
            return predict_binned_cpu(booster, Xp, num_iteration=num_iteration)

        return fn

    def _build_jax(self, entry):
        import jax.numpy as jnp

        from dryad_tpu.cpu.predict import rf_average
        from dryad_tpu.engine.predict import _accumulate

        trees_dev, init_dev = entry.device_state()
        _, _, n_iter = entry.staged()
        booster = entry.booster
        depth = max(booster.max_depth_seen, 1)
        is_rf = booster.params.boosting == "rf" and n_iter > 0

        def fn(Xp):
            # trees/init are device-resident arguments; jit specializes on
            # the (bucket, F) shape of Xp — one XLA program per bucket
            raw = np.asarray(_accumulate(trees_dev, jnp.asarray(Xp),
                                         init_dev, depth))
            if is_rf:
                raw = rf_average(raw, booster.init_score, n_iter)
            return raw

        return fn
