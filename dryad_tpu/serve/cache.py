"""Shape-bucketed compiled-predict cache, single-device AND sharded.

jit specializes on array shapes, so every distinct request size would
compile (and through a remote-TPU tunnel, compile *slowly*).  Instead,
batches are padded up to the next power-of-two row bucket and predicted
at the bucket shape; warm traffic then touches a small fixed set of
programs — at most log2(max_bucket / min_bucket) + 1 per model version
and shard arm — and never recompiles.  Batches larger than
``max_bucket`` are predicted in ``max_bucket``-row chunks.

Entries come in two families keyed by (version, bucket, n_shards):

* ``n_shards == 1`` — the single-device jitted accumulate (fast path for
  small interactive batches).
* ``n_shards == mesh size`` — ``engine.predict.sharded_accumulate_fn``:
  the padded row bucket sharded over the mesh, trees replicated, no
  collectives; one implicit gather at the result edge when the host
  fetches.  Routing is deterministic per bucket (``bucket × num_outputs
  >= sharded_threshold``), so warming every bucket warms exactly the arm
  that bucket will use forever — warm traffic stays recompile-free
  across BOTH families.

The dispatch pipeline (batcher.py) needs host work separated from device
work, so prediction is split: ``prepare_raw`` does the host-side
chunk/bucket/pad and entry resolution, ``execute_raw`` runs the compiled
programs and performs the ONE real host fetch per chunk (np.asarray on
the raw result — never ``block_until_ready``, which lies on the tunnel).
``predict_raw`` composes the two for serial callers.

Bitwise contract: padding rows (bin 0 everywhere), chunking, and row
sharding cannot change the real rows' scores.  Tree traversal and fp32
leaf accumulation are strictly per-row (one scan carry element per row,
no cross-row reduction anywhere in predict), so a padded or sharded
program computes exactly the same per-row arithmetic as an unpadded
single-device one — the parity is structural, not approximate, and
tests/test_serve.py + tests/test_serve_sharded.py pin it.

Compiled callables never close over device arrays: they re-resolve
``entry.device_state()`` per call, so a registry eviction actually frees
the buffers and a re-staged model is picked up transparently with no
recompile (jit caches on shape, not array identity).

The cache also serves the no-device fallback: with ``backend='cpu'`` the
per-bucket entry wraps the canonical numpy predict instead of a jitted
program.  Bucketing is kept there too so batching behavior, metrics, and
the warmup discipline are identical on both backends.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np


def bucket_rows(n: int, min_bucket: int = 8,
                max_bucket: Optional[int] = None) -> int:
    """Smallest power of two >= n, floored at min_bucket, capped at
    max_bucket (itself rounded up to a power of two by the server)."""
    if n < 1:
        raise ValueError("bucket_rows needs n >= 1")
    b = max(int(min_bucket), 1 << (int(n) - 1).bit_length())
    if max_bucket is not None:
        b = min(b, int(max_bucket))
    return b


class PreparedPredict:
    """Host-side-ready predict work: padded chunks + their resolved
    compiled callables.  Built by ``prepare_raw`` (pipeline stage A),
    consumed by ``execute_raw`` (stage B)."""

    __slots__ = ("entry", "n", "chunks")

    def __init__(self, entry, n: int, chunks: list):
        self.entry = entry
        self.n = n
        self.chunks = chunks    # [(fn, padded_chunk, start, m), ...]


class CompiledPredictCache:
    """(version, bucket, n_shards) → prepared predict callable, with
    hit/compile accounting.  ``backend`` is 'jax' (device-resident jitted
    accumulate, optionally sharded over ``mesh``) or 'cpu' (canonical
    numpy predict)."""

    GUARDED_BY = {"_fns": "_lock", "_warm": "_lock"}

    def __init__(self, backend: str = "cpu", metrics=None, *,
                 min_bucket: int = 8, max_bucket: int = 4096,
                 mesh=None, sharded_threshold: Optional[int] = None):
        if backend not in ("jax", "cpu"):
            raise ValueError(f"unknown cache backend {backend!r}")
        self.backend = backend
        self.metrics = metrics
        self.min_bucket = int(min_bucket)
        # cap must be a power of two so chunk remainders re-bucket cleanly
        self.max_bucket = 1 << (int(max_bucket) - 1).bit_length()
        # sharding: None threshold disables the sharded family entirely
        self.mesh = mesh if backend == "jax" else None
        self.n_shards = (int(np.prod(mesh.devices.shape))
                         if self.mesh is not None else 1)
        self.sharded_threshold = (None if sharded_threshold is None
                                  else int(sharded_threshold))
        # one prepared callable per (version, n_shards) — the callable is
        # shape-agnostic; on the jax path the per-shape specialization
        # lives in jit's own cache — plus per-(version, bucket, n_shards)
        # warmth accounting: the first call at a bucket shape is what
        # triggers an XLA compile.  The lock covers _fns/_warm: the
        # collector thread inserts via _get while an admin thread may
        # purge via evict_version
        self._lock = threading.Lock()
        self._fns: dict[tuple, object] = {}
        self._warm: set[tuple] = set()
        # recompile tripwire (r12, obs/tripwire.py): a fresh cache
        # legitimately compiles during warmup; once ``warmup_complete()``
        # arms the family, any NEW (version, bucket, shards) key raises
        # ``dryad_recompile_unexpected_total`` and degrades /healthz —
        # the "zero recompiles after warmup" test assertion as a live
        # production alarm.  begin_program here resets the family for
        # this cache's generation (the jax-free obs side; host keys only).
        from dryad_tpu.obs.tripwire import default_tripwire

        self._tripwire = default_tripwire()
        self._tripwire.begin_program("serve.predict")

    @property
    def num_entries(self) -> int:
        """Warm (version, bucket, shards) keys — compiled shapes, not
        closures."""
        with self._lock:
            return len(self._warm)

    def warmup_complete(self) -> None:
        """Declare the expected-compile budget spent: every bucket this
        cache can produce has been touched (``buckets()`` is the warmup
        set and shard routing is deterministic per bucket), so any later
        cold key is an UNEXPECTED recompile — counter + degraded
        /healthz, not just a slow request.  Re-arming after a deploy (or
        a fired alarm) clears the standing degradation — re-warm +
        re-arm IS the recovery path."""
        self._tripwire.arm("serve.predict")

    def deploy_started(self) -> None:
        """Open a deploy window (a model load legitimately compiles new
        programs): disarm without forgetting warm keys; the caller warms
        the new version's buckets and calls ``warmup_complete()`` again."""
        self._tripwire.disarm("serve.predict")

    def buckets(self) -> list[int]:
        """Every bucket size this cache can ever produce — the warmup set.
        Routing to the shard arm is a pure function of the bucket, so
        touching each bucket once warms both families completely."""
        out, b = [], self.min_bucket
        while b <= self.max_bucket:
            out.append(b)
            b <<= 1
        return out

    def shards_for(self, bucket: int, num_outputs: int) -> int:
        """Deterministic shard-arm routing: the sharded family only when a
        mesh is attached, the bucket divides it, and the bucket carries
        enough row-outputs of work to beat the single-device dispatch."""
        if (self.mesh is None or self.sharded_threshold is None
                or self.n_shards <= 1):
            return 1
        if bucket % self.n_shards != 0:
            return 1
        return (self.n_shards
                if bucket * int(num_outputs) >= self.sharded_threshold else 1)

    # ---- prediction --------------------------------------------------------
    def prepare_raw(self, entry, Xb: np.ndarray) -> PreparedPredict:
        """HOST stage: chunk at max_bucket, bucket, zero-pad, and resolve
        each chunk's compiled callable (warmth accounting happens here).
        No device work — safe to overlap with an in-flight execute."""
        n = int(Xb.shape[0])
        chunks = []
        for start in range(0, n, self.max_bucket):
            chunk = Xb[start:start + self.max_bucket]
            m = int(chunk.shape[0])
            b = bucket_rows(m, self.min_bucket, self.max_bucket)
            fn = self._get(entry, b, self.shards_for(b, entry.num_outputs))
            if m < b:
                # concatenate already yields a fresh contiguous array; the
                # old ascontiguousarray pre-copy doubled the pad-path copy
                pad = np.zeros((b - m,) + chunk.shape[1:], chunk.dtype)
                chunk = np.concatenate([chunk, pad])
            chunks.append((fn, chunk, start, m))
        return PreparedPredict(entry, n, chunks)

    def execute_raw(self, prepared: PreparedPredict) -> np.ndarray:
        """DEVICE stage: run the compiled programs; the np.asarray inside
        each ``fn`` is the single real host fetch per chunk."""
        out = np.empty((prepared.n, prepared.entry.num_outputs), np.float32)
        for fn, chunk, start, m in prepared.chunks:
            out[start:start + m] = fn(chunk)[:m]
        return out

    def predict_raw(self, entry, Xb: np.ndarray) -> np.ndarray:
        """Raw scores (n, K) fp32 for pre-binned rows, through the bucketed
        compiled program; bitwise equal to the direct unpadded predict."""
        if int(Xb.shape[0]) == 0:
            return np.zeros((0, entry.num_outputs), np.float32)
        return self.execute_raw(self.prepare_raw(entry, Xb))

    # ---- entry construction ------------------------------------------------
    def _get(self, entry, bucket: int, n_shards: int):
        key = (entry.version, bucket, n_shards)
        with self._lock:
            hit = key in self._warm
            if not hit:
                self._warm.add(key)
            fkey = (entry.version, n_shards)
            fn = self._fns.get(fkey)
            if fn is None:
                # closure construction is cheap and pure (the compile
                # happens at first call, outside the lock)
                fn = (self._build_jax(entry, n_shards)
                      if self.backend == "jax" else self._build_cpu(entry))
                self._fns[fkey] = fn
        if not hit:
            # cold key = a compile boundary; after warmup_complete() a new
            # key here fires the recompile tripwire (exactly once per key)
            self._tripwire.note_compile(
                "serve.predict", key,
                detail=f"version={key[0]} bucket={key[1]} shards={key[2]}")
        if self.metrics is not None:
            self.metrics.record_cache(hit, entry.version)
        return fn

    def evict_version(self, version: int) -> None:
        """Drop a version's prepared callables + warmth keys (model
        unloaded): the closures hold the ModelEntry (and through it the
        booster) alive, so an unload without this purge would leak every
        co-served model ever retired.  (An in-flight _get racing this can
        re-insert one tiny closure for the dead version, but the entry is
        closed by then — its staged() raises, so nothing big gets pinned
        and the in-flight group fails like any unloaded-mid-queue group.)"""
        version = int(version)
        with self._lock:
            for key in [k for k in self._fns if k[0] == version]:
                del self._fns[key]
            self._warm -= {k for k in self._warm if k[0] == version}

    def _build_cpu(self, entry):
        from dryad_tpu.cpu.predict import predict_binned_cpu

        booster, num_iteration = entry.booster, entry.num_iteration

        def fn(Xp):
            return predict_binned_cpu(booster, Xp, num_iteration=num_iteration)

        return fn

    def _build_jax(self, entry, n_shards: int):
        import jax
        import jax.numpy as jnp

        from dryad_tpu.cpu.predict import rf_average
        from dryad_tpu.engine import introspect
        from dryad_tpu.engine.predict import _accumulate, sharded_accumulate_fn

        booster = entry.booster
        depth = max(booster.max_depth_seen, 1)
        is_rf = booster.params.boosting == "rf"
        mesh = self.mesh if n_shards > 1 else None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from dryad_tpu.engine.distributed import AXIS

            acc = sharded_accumulate_fn(mesh, depth)
            row_sharding = NamedSharding(mesh, P(AXIS, None))

        def fn(Xp):
            # device_state is re-resolved EVERY call so a registry
            # eviction's re-stage is picked up transparently — jit caches
            # on shape/dtype, not array identity, so this never recompiles
            trees_dev, init_dev = entry.device_state(mesh)
            # r21: the staged dict's keys carry the traversal layout —
            # packed node-word tables dispatch the packed program per
            # bucket with no cache-side branching, and a re-stage under a
            # different predict_layout retraces via the pytree structure
            # (the version in the key keeps introspection honest too)
            layout = "packed" if "node_word" in trees_dev else "legacy"
            # compile-boundary introspection (memoized per shape; the
            # cache-level _get already notes the tripwire key, so the
            # capture only records dryad_prog_* cost series)
            if mesh is not None:
                Xd = jax.device_put(Xp, row_sharding)
                introspect.capture(
                    "serve.predict",
                    (entry.version, Xp.shape, n_shards, depth,
                     trees_dev["value"].shape, layout),
                    acc, trees_dev, Xd, init_dev, note_tripwire=False,
                    labels={"bucket": Xp.shape[0], "shards": n_shards,
                            "layout": layout})
                raw = np.asarray(acc(trees_dev, Xd, init_dev))
            else:
                Xj = jnp.asarray(Xp)
                introspect.capture(
                    "serve.predict",
                    (entry.version, Xp.shape, 1, depth,
                     trees_dev["value"].shape, layout),
                    _accumulate, trees_dev, Xj, init_dev, depth,
                    note_tripwire=False,
                    labels={"bucket": Xp.shape[0], "shards": 1,
                            "layout": layout})
                raw = np.asarray(_accumulate(trees_dev, Xj, init_dev,
                                             depth))
            if is_rf:
                _, _, n_iter = entry.staged()
                if n_iter > 0:
                    raw = rf_average(raw, booster.init_score, n_iter)
            return raw

        return fn
