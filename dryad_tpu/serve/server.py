"""PredictServer: the long-lived online-inference front object.

Composes the registry (versioned, hot-swappable, device-resident
models), the shape-bucketed compiled-predict cache, and the
micro-batching queue behind one thread-safe ``predict`` call, with a
``stats()`` snapshot for observability.  ``python -m dryad_tpu serve``
wraps this in an HTTP front end (serve/http.py).

Backend resolution ('auto') prefers the device path when an accelerator
is attached and falls back gracefully to the canonical numpy predict
when no device can be initialized — the serving semantics (bucketing,
batching, metrics, bitwise parity with ``Booster.predict``) are
identical on both paths.
"""

from __future__ import annotations

import time
import warnings
from typing import Optional

import numpy as np

from dryad_tpu.serve.batcher import MicroBatcher, Request
from dryad_tpu.serve.cache import CompiledPredictCache
from dryad_tpu.serve.metrics import ServeMetrics
from dryad_tpu.serve.registry import ModelRegistry


def _resolve_backend(backend: str) -> str:
    """'auto'|'tpu'|'cpu' → 'jax' (device predict) or 'cpu' (numpy).

    'tpu' runs the jit path on whatever platform jax initializes (the
    test mesh is 8 virtual CPU devices); 'auto' takes the jit path only
    when a real accelerator is attached.  Device-init failure degrades to
    the numpy path with a warning instead of killing the server.
    """
    if backend == "cpu":
        return "cpu"
    if backend not in ("auto", "tpu"):
        raise ValueError(f"unknown backend {backend!r}")
    try:
        import jax

        devices = jax.devices()
    except Exception as e:  # noqa: BLE001 — any device-init failure degrades
        warnings.warn(f"device init failed ({e!r}); serving on CPU")
        return "cpu"
    if backend == "tpu":
        return "jax"
    return "jax" if any(d.platform != "cpu" for d in devices) else "cpu"


class PredictServer:
    def __init__(self, registry: Optional[ModelRegistry] = None, *,
                 backend: str = "auto", max_batch_rows: int = 4096,
                 max_wait_ms: float = 2.0, queue_size: int = 256,
                 min_bucket: int = 8, latency_window: int = 4096):
        self.registry = registry if registry is not None else ModelRegistry()
        self.backend = _resolve_backend(backend)
        self.metrics = ServeMetrics(latency_window=latency_window)
        self.cache = CompiledPredictCache(
            self.backend, self.metrics,
            min_bucket=min_bucket, max_bucket=max_batch_rows)
        self.batcher = MicroBatcher(
            self._dispatch, max_batch_rows=max_batch_rows,
            max_wait_ms=max_wait_ms, queue_size=queue_size,
            metrics=self.metrics)

    # ---- lifecycle ---------------------------------------------------------
    def start(self) -> "PredictServer":
        self.batcher.start()
        return self

    def stop(self) -> None:
        self.batcher.stop()

    def __enter__(self) -> "PredictServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- model lifecycle (thin registry passthroughs) ----------------------
    def load_model(self, path: str, *, activate: bool = True,
                   num_iteration: Optional[int] = None) -> int:
        return self.registry.load(path, activate=activate,
                                  num_iteration=num_iteration)

    def activate(self, version: int) -> None:
        self.registry.activate(version)

    def rollback(self) -> int:
        return self.registry.rollback()

    # ---- request path ------------------------------------------------------
    def predict(self, X: np.ndarray, *, version: Optional[int] = None,
                raw_score: bool = False, binned: bool = False,
                timeout: Optional[float] = None) -> np.ndarray:
        """Predict through the full serving stack (bin → bucket → batch →
        compiled predict → link transform); bitwise equal to the direct
        ``Booster.predict`` / ``predict_binned`` on the same rows."""
        self.start()
        entry = self.registry.get(version)   # pin the version at submit time
        X = np.asarray(X)
        if X.ndim == 1:
            X = X[None, :]
        if binned:
            Xb = np.ascontiguousarray(X)
        else:
            Xb = entry.booster.mapper.transform(np.asarray(X, np.float32))
        if Xb.shape[0] == 0:
            # empty request: no dispatch, same output shape/dtype contract
            t0 = time.perf_counter()
            raw = np.zeros((0, entry.num_outputs), np.float32)
            out = entry.booster.transform_raw(raw, raw_score=raw_score)
            self.metrics.record_request(0, time.perf_counter() - t0)
            return out
        req = Request(Xb, version=entry.version, raw_score=raw_score)
        return self.batcher.submit(req, timeout=timeout)

    def _dispatch(self, batch: list[Request]) -> list[np.ndarray]:
        """Coalesced batch → per-request outputs.  Requests are grouped by
        model version (a hot-swap mid-queue may interleave versions); each
        group is one concatenated bucketed predict, sliced back per
        request.  Per-row arithmetic makes the slicing bitwise-exact."""
        results: list = [None] * len(batch)
        groups: dict[int, list[int]] = {}
        for i, req in enumerate(batch):
            groups.setdefault(req.version, []).append(i)
        for version, idxs in groups.items():
            try:
                entry = self.registry.get(version)
                if len(idxs) == 1:
                    X = batch[idxs[0]].rows
                else:
                    X = np.concatenate([batch[i].rows for i in idxs], axis=0)
                raw = self.cache.predict_raw(entry, X)
                offset = 0
                for i in idxs:
                    n = batch[i].rows.shape[0]
                    results[i] = entry.booster.transform_raw(
                        raw[offset:offset + n], raw_score=batch[i].raw_score)
                    offset += n
            except Exception as e:  # noqa: BLE001 — e.g. a version unloaded
                # mid-queue; fail only this group's requests, not the batch
                for i in idxs:
                    results[i] = e
        return results

    # ---- observability -----------------------------------------------------
    def stats(self) -> dict:
        snap = self.metrics.snapshot()
        snap["backend"] = self.backend
        snap["active_version"] = self.registry.active_version
        snap["versions"] = self.registry.versions()
        snap["compiled_buckets"] = self.cache.num_entries
        return snap
