"""PredictServer: the long-lived online-inference front object.

Composes the registry (versioned, named, hot-swappable, device-resident
models under an LRU memory budget), the shape-bucketed compiled-predict
cache (single-device + sharded entry families), and the micro-batching
queue's overlapped dispatch pipeline behind one thread-safe ``predict``
call, with a ``stats()`` snapshot for observability.  ``python -m
dryad_tpu serve`` wraps this in an HTTP front end (serve/http.py).

Backend resolution ('auto') prefers the device path when an accelerator
is attached and falls back gracefully to the canonical numpy predict
when no device can be initialized — the serving semantics (bucketing,
batching, metrics, bitwise parity with ``Booster.predict``) are
identical on both paths.

Sharded predict: on the device path with a multi-device mesh, buckets
whose rows × outputs clear ``sharded_threshold`` run under ``shard_map``
with rows split over the mesh (``sharded='auto'``; ``True`` forces every
bucket onto the mesh, ``False`` disables it).  Small interactive batches
stay on the single-device fast path either way.

The dispatch pipeline splits each coalesced batch into ``_prepare``
(host: group by version, concatenate, bucket-pad, resolve compiled
entries) and ``_execute`` (device: run programs + the one real host
fetch, then per-request slice/transform) so batch i+1's host work
overlaps batch i's device work (batcher.py; ``pipeline_depth=1`` forces
the old strictly serial loop, kept as the bench comparison arm).
"""

from __future__ import annotations

import time
import warnings
from typing import Optional

import numpy as np

from dryad_tpu.serve.batcher import MicroBatcher, Request, RequestTrace
from dryad_tpu.serve.cache import CompiledPredictCache
from dryad_tpu.serve.metrics import ServeMetrics
from dryad_tpu.serve.registry import ModelRegistry


_DRIFT_UNSET = object()      # "not probed yet" marker in the monitor table


def _resolve_backend(backend: str) -> str:
    """'auto'|'tpu'|'cpu' → 'jax' (device predict) or 'cpu' (numpy).

    'tpu' runs the jit path on whatever platform jax initializes (the
    test mesh is 8 virtual CPU devices); 'auto' takes the jit path only
    when a real accelerator is attached.  Device-init failure degrades to
    the numpy path with a warning instead of killing the server.
    """
    if backend == "cpu":
        return "cpu"
    if backend not in ("auto", "tpu"):
        raise ValueError(f"unknown backend {backend!r}")
    try:
        import jax

        devices = jax.devices()
    except Exception as e:  # noqa: BLE001 — any device-init failure degrades
        warnings.warn(f"device init failed ({e!r}); serving on CPU")
        return "cpu"
    if backend == "tpu":
        return "jax"
    return "jax" if any(d.platform != "cpu" for d in devices) else "cpu"


class _PreparedGroup:
    """One model-version group of a prepared batch (see _prepare).
    ``drift`` is the version's DriftMonitor (or None): _prepare observed
    the binned features into it and _execute observes the raw scores —
    the handoff queue's happens-before makes the plain field safe."""

    __slots__ = ("idxs", "entry", "prepared", "row_counts", "raw_flags",
                 "error", "drift")

    def __init__(self, idxs, entry=None, prepared=None, row_counts=None,
                 raw_flags=None, error=None, drift=None):
        self.idxs = idxs
        self.entry = entry
        self.prepared = prepared
        self.row_counts = row_counts
        self.raw_flags = raw_flags
        self.error = error
        self.drift = drift


class PredictServer:
    def __init__(self, registry: Optional[ModelRegistry] = None, *,
                 backend: str = "auto", max_batch_rows: int = 4096,
                 max_wait_ms: float = 2.0, queue_size: int = 256,
                 min_bucket: int = 8, latency_window: int = 4096,
                 pipeline_depth: int = 2, sharded="auto",
                 sharded_threshold: Optional[int] = None,
                 device_budget_bytes: Optional[int] = None,
                 drift="auto", drift_window: int = 8192):
        self.backend = _resolve_backend(backend)
        self.metrics = ServeMetrics(latency_window=latency_window)
        # drift monitors (obs/drift.py) are per model version, created
        # lazily at first dispatch for versions whose artifact carries a
        # reference profile.  The zero-cost contract: with the obs
        # registry disabled at construction (DRYAD_OBS=0) — or with
        # drift off — the table stays None and the request path never
        # allocates drift state (one attr check per batch, pinned by
        # tracemalloc in tests/test_drift.py).
        self.drift_window = int(drift_window)
        drift_on = (drift not in (False, 0, "off", "none")
                    and self.drift_window > 0 and self.metrics.obs_enabled)
        self._drift_monitors: Optional[dict] = {} if drift_on else None
        if registry is not None:
            self.registry = registry
            # a caller-supplied registry still honors this server's budget
            # unless it already carries its own
            if (device_budget_bytes is not None
                    and self.registry.budget_bytes is None):
                self.registry.budget_bytes = int(device_budget_bytes)
        else:
            self.registry = ModelRegistry(budget_bytes=device_budget_bytes)
        if self.registry.metrics is None:
            self.registry.metrics = self.metrics
        self.mesh = self._make_mesh(sharded)
        if sharded_threshold is None:
            # r23: the live default comes from the policy table (the
            # committed value IS predict.SHARDED_MIN_WORK; a calibrated
            # device entry can move it without a redeploy)
            from dryad_tpu.policy.gates import gate_value

            sharded_threshold = int(gate_value("predict_sharded",
                                               "min_work"))
        # threshold in rows × outputs; sharded=True forces the mesh arm for
        # every bucket, False (or a 1-device mesh) disables it entirely.
        # NOTE the interplay with max_batch_rows: buckets cap there, so at
        # the default 4096-row cap 'auto' (32k row-outputs) shards only
        # wide-output models (K >= 8) — by design: sharding a 4096-row
        # binary dispatch is dispatch-bound and loses to the single-device
        # program.  Giant-batch bulk scoring should raise max_batch_rows
        # (or force sharded=True), which is what unlocks the mesh for K=1.
        threshold = (None if self.mesh is None
                     else 0 if sharded is True else int(sharded_threshold))
        self.cache = CompiledPredictCache(
            self.backend, self.metrics,
            min_bucket=min_bucket, max_bucket=max_batch_rows,
            mesh=self.mesh, sharded_threshold=threshold)
        self.batcher = MicroBatcher(
            self._dispatch, prepare=self._prepare, execute=self._execute,
            pipeline_depth=pipeline_depth, max_batch_rows=max_batch_rows,
            max_wait_ms=max_wait_ms, queue_size=queue_size,
            metrics=self.metrics)

    def _make_mesh(self, sharded):
        if self.backend != "jax" or sharded is False:
            return None
        import jax

        devices = jax.devices()
        if len(devices) < 2:
            return None
        from dryad_tpu.engine.distributed import make_mesh

        return make_mesh(devices)

    # ---- lifecycle ---------------------------------------------------------
    def start(self) -> "PredictServer":
        self.batcher.start()
        return self

    def stop(self) -> None:
        self.batcher.stop()

    def __enter__(self) -> "PredictServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- model lifecycle (thin registry passthroughs) ----------------------
    def load_model(self, path: str, *, activate: bool = True,
                   num_iteration: Optional[int] = None,
                   name: Optional[str] = None) -> int:
        # a deploy legitimately compiles the new version's buckets: open
        # the tripwire's deploy window (no-op when never armed) so a
        # routine model load can't latch /healthz at 503 — warm the new
        # version (``warmup``) and re-arm (``warmup_complete``) to close it
        self.cache.deploy_started()
        return self.registry.load(path, activate=activate,
                                  num_iteration=num_iteration, name=name)

    def activate(self, version: int) -> None:
        self.registry.activate(version)

    def rollback(self) -> int:
        return self.registry.rollback()

    def unload(self, version: int) -> None:
        """Unload a version AND purge its compiled-cache closures — the
        registry alone cannot free those (they hold the entry alive)."""
        self.registry.unload(version)
        self.cache.evict_version(version)

    def warmup(self, versions=None) -> int:
        """Structural warmup through the real compiled-predict path: one
        zero-binned batch per (version, bucket) — ``cache.buckets()`` is
        the complete reachable set and shard routing is deterministic per
        bucket, so this compiles every program warm traffic can ever hit
        — then arm the recompile tripwire (``warmup_complete``).  This is
        the PRODUCTION arming path: the serve CLI runs it with
        ``--warmup``; serve/bench.py does the equivalent with real
        feature batches.  Returns the number of (version, bucket) pairs
        touched."""
        if versions is None:
            versions = self.registry.versions()
        touched = 0
        for version in versions:
            entry = self.registry.get(version)
            mapper = entry.booster.mapper
            for b in self.cache.buckets():
                Xb = np.zeros((b, mapper.num_features), mapper.bin_dtype)
                self.cache.predict_raw(entry, Xb)
                touched += 1
        self.warmup_complete()
        return touched

    def warmup_complete(self) -> None:
        """Arm the recompile tripwire (obs/tripwire.py): the caller has
        touched every bucket it intends to serve warm, so any later cold
        compiled-entry key increments
        ``dryad_recompile_unexpected_total{program="serve.predict"}`` and
        degrades ``/healthz`` — the live form of the "zero recompiles
        after warmup" invariant.  ``warmup()`` / serve/bench.py call this
        after their structural warmups; re-arming after a deploy clears
        the standing degradation (the recovery path)."""
        self.cache.warmup_complete()

    # ---- request path ------------------------------------------------------
    def predict(self, X: np.ndarray, *, version: Optional[int] = None,
                model: Optional[str] = None, raw_score: bool = False,
                binned: bool = False,
                timeout: Optional[float] = None,
                trace: Optional[str] = None,
                priority: Optional[str] = None) -> np.ndarray:
        """Predict through the full serving stack (bin → bucket → batch →
        compiled predict → link transform); bitwise equal to the direct
        ``Booster.predict`` / ``predict_binned`` on the same rows.
        Routing: ``version`` pins an exact version, ``model`` routes by
        registry name; default is the active version.  ``trace`` is the
        propagated request trace id (``X-Dryad-Trace`` — the HTTP front
        end passes it through) and ``priority`` the admission class; both
        feed the per-(priority, stage) latency series and the span ring,
        and cost nothing when obs is disabled (no context is allocated)."""
        self.start()
        # pin the version at submit time (a name is resolved here too, so
        # a mid-queue re-deploy under the same name can't switch models)
        entry = self.registry.get(version, name=model)
        X = np.asarray(X)
        if X.ndim == 1:
            X = X[None, :]
        if binned:
            Xb = np.ascontiguousarray(X)
        else:
            # binning is DEFERRED to _prepare: it rides the dispatch
            # pipeline's host stage, overlapped with the in-flight device
            # predict (dtype is coerced here so _prepare can concatenate
            # requests without widening surprises)
            Xb = np.ascontiguousarray(np.asarray(X, np.float32))
        # validate the feature width HERE, in the caller's thread: binning
        # is deferred into the coalesced _prepare, and without this check
        # one malformed request would poison every co-batched request of
        # the same version (raw width is the BASE mapper's for bundled
        # mappers — transform folds it down to num_features)
        mapper = entry.booster.mapper
        nf = (mapper.num_features if binned
              else getattr(mapper, "base", mapper).num_features)
        if Xb.ndim != 2 or Xb.shape[1] != nf:
            raise ValueError(
                f"request shape {Xb.shape} does not match model version "
                f"{entry.version}: expected (n, {nf}) "
                f"{'binned' if binned else 'raw'} features")
        if Xb.shape[0] == 0:
            # empty request: no dispatch, same output shape/dtype contract
            t0 = time.perf_counter()
            raw = np.zeros((0, entry.num_outputs), np.float32)
            out = entry.booster.transform_raw(raw, raw_score=raw_score)
            self.metrics.record_request(0, time.perf_counter() - t0,
                                        entry.version)
            return out
        # trace context only when obs records — the zero-cost contract:
        # with the registry disabled the request path allocates nothing
        # beyond the Request it always built
        tctx = (RequestTrace(trace, priority or "interactive")
                if self.metrics.obs_enabled else None)
        req = Request(Xb, version=entry.version, raw_score=raw_score,
                      binned=binned, priority=priority or "interactive",
                      tctx=tctx)
        return self.batcher.submit(req, timeout=timeout)

    # ---- dispatch (serial) / prepare + execute (pipeline) ------------------
    def _prepare(self, batch: list[Request]) -> list[_PreparedGroup]:
        """HOST stage: group the coalesced batch by (model version, binned)
        — a hot-swap mid-queue may interleave versions — concatenate each
        group's rows, BIN the raw-feature groups through the model's
        frozen mapper, and run the cache's host-side bucket/pad + entry
        resolution.  Binning is per-row, so batching it here is bitwise
        equal to per-request binning.  A dead group (e.g. its version was
        unloaded mid-queue) carries its error instead of poisoning the
        batch."""
        groups: dict[tuple, list[int]] = {}
        for i, req in enumerate(batch):
            groups.setdefault((req.version, req.binned), []).append(i)
        out = []
        for (version, binned), idxs in groups.items():
            try:
                entry = self.registry.get(version)
                if len(idxs) == 1:
                    X = batch[idxs[0]].rows
                else:
                    X = np.concatenate([batch[i].rows for i in idxs], axis=0)
                if not binned:
                    X = entry.booster.mapper.transform(X)
                # drift accounting on the already-binned batch: the
                # monitor counts the SAME bin ids the compiled predict is
                # about to consume, so covariate drift is measured in the
                # model's own split space (zero extra binning work)
                mon = None
                if self._drift_monitors is not None:
                    mon = self._drift_monitor(entry)
                    if mon is not None:
                        mon.observe_features(X)
                out.append(_PreparedGroup(
                    idxs, entry, self.cache.prepare_raw(entry, X),
                    [batch[i].rows.shape[0] for i in idxs],
                    [batch[i].raw_score for i in idxs], drift=mon))
            except Exception as e:  # noqa: BLE001 — fail only this group
                out.append(_PreparedGroup(idxs, error=e))
        return out

    def _execute(self, prepared: list[_PreparedGroup]) -> list:
        """DEVICE stage: run each group's compiled programs (one real host
        fetch per chunk inside the cache), then slice + link-transform per
        request.  Per-row arithmetic makes the slicing bitwise-exact."""
        n = 1 + max(i for g in prepared for i in g.idxs)
        results: list = [None] * n
        for g in prepared:
            if g.error is not None:
                for i in g.idxs:
                    results[i] = g.error
                continue
            try:
                raw = self.cache.execute_raw(g.prepared)
                if g.drift is not None:
                    # score-shift accounting on the raw margins the one
                    # real host fetch just delivered (pre-link: the raw
                    # score space is objective-invariant and matches the
                    # profile's train/valid histograms)
                    g.drift.observe_scores(raw)
                offset = 0
                for i, rows, raw_flag in zip(g.idxs, g.row_counts,
                                             g.raw_flags):
                    results[i] = g.entry.booster.transform_raw(
                        raw[offset:offset + rows], raw_score=raw_flag)
                    offset += rows
            except Exception as e:  # noqa: BLE001 — fail only this group
                for i in g.idxs:
                    results[i] = e
        return results

    def _dispatch(self, batch: list[Request]) -> list:
        """Serial-mode dispatch: the pipeline stages composed in-line."""
        return self._execute(self._prepare(batch))

    # ---- drift monitors (obs/drift.py) -------------------------------------
    def _drift_monitor(self, entry):
        """The version's monitor, created on first dispatch when the
        model carries a reference profile (None cached otherwise, so a
        profile-less model costs one dict probe per batch).  Runs on the
        collector thread only; _execute reads the group's stashed handle
        after the handoff (happens-before via the pipeline queue)."""
        table = self._drift_monitors
        mon = table.get(entry.version, _DRIFT_UNSET)
        if mon is _DRIFT_UNSET:
            profile = getattr(entry.booster, "profile", None)
            if profile is None:
                mon = None
            else:
                from dryad_tpu.obs.drift import DriftMonitor

                # the model label prefers the registry alias (operators
                # name models, not versions); the version pins it apart
                # from a re-push under the same name
                names = [n for n, v in self.registry.aliases().items()
                         if v == entry.version]
                label = names[0] if names else f"v{entry.version}"
                mon = DriftMonitor(
                    profile.feature_counts,
                    ref_score_state=profile.score_hist.get("train"),
                    model=label, window_rows=self.drift_window,
                    registry=self.metrics.obs_registry)
            table[entry.version] = mon
        return mon

    def drift_state(self) -> dict:
        """Raw drift blocks by model label — the replica's ``/obs``
        section the fleet router count-merges exactly."""
        if not self._drift_monitors:
            return {}
        out = {}
        # snapshot the table in one C-level copy: the collector thread
        # inserts new versions' monitors concurrently, and iterating the
        # live view would raise "dict changed size during iteration"
        # under a mid-deploy scrape
        for mon in list(self._drift_monitors.values()):
            if mon is not None:
                block = mon.export_state()
                out[block["model"]] = block
        return out

    def drift_report(self, budget_psi: Optional[float] = None) -> dict:
        """Local PSI verdicts by model label (also refreshes the
        ``dryad_drift_*`` gauges)."""
        if not self._drift_monitors:
            return {}
        return {mon.model: mon.snapshot(budget_psi)
                for mon in list(self._drift_monitors.values())
                if mon is not None}

    # ---- observability -----------------------------------------------------
    def stats(self) -> dict:
        snap = self.metrics.snapshot()
        snap["backend"] = self.backend
        snap["active_version"] = self.registry.active_version
        snap["versions"] = self.registry.versions()
        snap["aliases"] = self.registry.aliases()
        snap["compiled_buckets"] = self.cache.num_entries
        snap["pipeline_depth"] = (self.batcher.pipeline_depth
                                  if self.batcher.pipelined else 1)
        snap["mesh_shards"] = self.cache.n_shards
        snap["sharded_threshold"] = self.cache.sharded_threshold
        snap["memory"] = self.registry.memory()
        from dryad_tpu.policy.gates import stats_block

        # r23: table provenance + newest decision per gate (incl. the
        # predict_layout fallback reason when a model serves legacy)
        snap["policy"] = stats_block()
        drift = self.drift_report()
        if drift:
            snap["drift"] = {
                model: {"rows": r["rows"], "psi_max": r["psi_max"],
                        "score_psi": r["score_psi"]}
                for model, r in drift.items()}
        return snap
