"""Micro-batching queue: coalesce concurrent requests into one dispatch,
with an optional two-deep overlapped dispatch pipeline.

The batcher is layout-agnostic by construction (r21): the traversal
table layout (packed node-word vs legacy — ``Params.predict_layout``)
is resolved once at registry staging time and reaches the device through
the compiled-cache programs this module dispatches, so per-bucket
batches run the packed program with no batcher-side branching and a
model pushed with a different layout simply resolves new cache entries.

A single collector thread drains a bounded queue.  The first dequeued
request opens a batch and starts a max-wait deadline clock; requests
keep joining until the row cap is reached or the deadline expires, then
the whole batch goes to the device in one dispatch.  Under load batches
fill instantly (the deadline never waits); when idle a lone request pays
at most ``max_wait_ms`` of extra latency.

Serial mode (``pipeline_depth <= 1`` or no prepare/execute split): the
collector also runs the dispatch, strictly one batch at a time.

Pipeline mode (the default when the caller provides ``prepare`` +
``execute``): the collector runs only the HOST side — coalescing plus
``prepare(batch)`` (grouping, concatenation, bucket padding, compiled-
entry resolution) — and hands the prepared batch to an executor thread
over a bounded queue.  While the executor runs batch i's device predict
and the single result host fetch, the collector is already coalescing
and preparing batch i+1.  The handoff queue holds ``pipeline_depth - 1``
prepared batches, capping run-ahead at ``pipeline_depth`` batches past
delivery (depth 2 mirrors the trainer's tunnel-safe run-ahead cap: an
unbounded pipeline queues unfetched device work until a >1-min fetch
dies — STATUS r5).  The collector/executor threads never touch the
device result themselves — the one real host fetch lives inside the
execute callable (cache.execute_raw), and scripts/ci.sh lints this file
against growing fetches.

Backpressure is the bounded queue itself: when it is full, ``submit``
fails fast with ``ServeOverloaded`` instead of letting latency grow
without bound.  Each caller may also bound its own wait with a
per-request timeout (``ServeTimeout``); an abandoned request's result is
simply dropped when the batch completes.

Results come back bitwise equal to solo predicts: the dispatch function
slices the coalesced output per request, and every predict stage is
per-row (see cache.py).  Pipelining changes only WHEN a batch runs, not
what runs — batches stay FIFO through the handoff queue.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

import numpy as np

from dryad_tpu.obs.spans import record_at, span


class ServeOverloaded(RuntimeError):
    """The request queue is full — shed load upstream."""


class ServeTimeout(TimeoutError):
    """The per-request timeout expired before the batch completed."""


class RequestTrace:
    """Per-request observability context across the batching hand-off.

    A request crosses three threads — the caller (submit), the collector
    (batch assembly), the executor (dispatch + fetch) — so its stage
    timestamps are STAMPED in place as it travels and emitted once, at
    delivery, as trace-tagged spans (the ring, obs/trace_export) and
    per-(priority, stage) histogram observations (metrics.record_stage).
    The queue/event hand-offs that move the request between threads
    already provide the happens-before edges that make the plain-field
    stamps safe: exactly one thread owns the context at a time.

    Zero-cost when disabled: the server allocates a RequestTrace ONLY
    when the obs registry records (``ServeMetrics.obs_enabled``); with
    obs off ``Request.tctx`` stays None and every stamp site is one
    attribute check (the spans null-context idiom, test-pinned)."""

    __slots__ = ("trace", "priority", "t_submit", "t_collect", "t_execute")

    def __init__(self, trace: Optional[str] = None,
                 priority: str = "interactive"):
        self.trace = trace
        self.priority = priority
        self.t_submit = 0.0
        self.t_collect = 0.0
        self.t_execute = 0.0

    def finish(self, t_end: float, metrics=None) -> None:
        """Emit the stage spans/observations (called once, at delivery).
        Spans go to the SAME registry the metrics mirror into — the
        allocation gate (``metrics.obs_enabled``), the stage histograms,
        and the span series must agree on one registry, or a private
        registry (tests) would allocate contexts whose spans then vanish
        against a disabled process default."""
        reg = metrics.obs_registry if metrics is not None else None
        for name, stage, a, b in (
                ("serve.request/queue_wait", "queue_wait",
                 self.t_submit, self.t_collect),
                ("serve.request/batch_assembly", "batch_assembly",
                 self.t_collect, self.t_execute),
                ("serve.request/predict", "predict",
                 self.t_execute, t_end)):
            dur = max(b - a, 0.0)
            record_at(name, a, dur, trace=self.trace, registry=reg)
            if metrics is not None:
                metrics.record_stage(stage, dur, priority=self.priority)


class Request:
    """One submitted predict request.  ``rows`` is pre-binned when
    ``binned`` is True, else raw float32 features — binning then happens
    in the dispatch pipeline's host stage (server._prepare), overlapped
    with the previous batch's device predict.  ``priority`` is the
    admission class the fleet router classified (per-priority latency
    series); ``tctx`` is the optional RequestTrace (None with obs off)."""

    __slots__ = ("rows", "version", "raw_score", "binned", "event", "result",
                 "error", "abandoned", "priority", "tctx")

    def __init__(self, rows: np.ndarray, version: Optional[int] = None,
                 raw_score: bool = False, binned: bool = True,
                 priority: str = "interactive",
                 tctx: Optional[RequestTrace] = None):
        self.rows = rows
        self.version = version
        self.raw_score = raw_score
        self.binned = binned
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.abandoned = False
        self.priority = priority
        self.tctx = tctx


_STOP = object()          # pipeline-internal handoff sentinel only


class _StopToken:
    """Generation-stamped stop request on the public queue.  A token only
    stops the worker while its generation is current: a start() issued
    AFTER a stop() timed out (worker stuck in a stalled dispatch) bumps
    the generation, leaving the still-queued token STALE — the unstuck
    worker ignores it and keeps serving instead of dying with nothing
    left to collect the queue.  An in-flight stop() is never cancelled
    this way (see start())."""

    __slots__ = ("gen",)

    def __init__(self, gen: int):
        self.gen = gen


class MicroBatcher:
    """Bounded-queue request coalescer around a batch dispatch function.

    ``dispatch(batch)`` receives the list of coalesced ``Request``s and
    returns one result per request, in order.  When ``prepare`` and
    ``execute`` are also given (``dispatch ≡ execute ∘ prepare``) and
    ``pipeline_depth >= 2``, dispatch runs as the overlapped two-stage
    pipeline described in the module docstring.

    Lock contract (r15, pinned by the guarded-by lint + the schedule
    harness): ``_lock`` guards the lifecycle triple — the worker handle
    ``_thread``, the stop-token generation ``_gen``, and the timed-out
    marker ``_stop_timed_out``.  Only ``start()``/``stop()``/
    ``_stop_live()`` take it, always briefly and never around the queue
    or a join: ``stop()`` snapshots the handle under the lock, blocks
    OUTSIDE it, then re-validates under the lock before clearing — the
    r9 stop/start generation race lived exactly in that window, and the
    harness drill re-opens it whenever ``_stop_live`` stops comparing
    generations.  The queue itself is the synchronization for the
    request path; per-request state rides each ``Request``'s own event.
    """

    GUARDED_BY = {"_thread": "_lock", "_gen": "_lock",
                  "_stop_timed_out": "_lock"}

    def __init__(self, dispatch, *, prepare=None, execute=None,
                 pipeline_depth: int = 2, max_batch_rows: int = 4096,
                 max_wait_ms: float = 2.0, queue_size: int = 256,
                 metrics=None):
        self._dispatch = dispatch
        self._prepare = prepare
        self._execute = execute
        self.pipeline_depth = int(pipeline_depth)
        self.pipelined = (prepare is not None and execute is not None
                         and self.pipeline_depth >= 2)
        self.max_batch_rows = int(max_batch_rows)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.metrics = metrics
        self._q: queue.Queue = queue.Queue(maxsize=int(queue_size))
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._gen = 0
        self._stop_timed_out = False

    # ---- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            if self._stop_timed_out:
                # a previous stop() timed out with its token still queued
                # behind the stuck dispatch: this start() is a deliberate
                # reinstatement, so invalidate that token — the unstuck
                # worker ignores it and keeps serving.  Only the timed-out
                # case is cancellable: an IN-FLIGHT stop() (join pending)
                # must survive predict()'s per-request auto-start, or any
                # concurrent traffic would silently abort a shutdown.
                self._gen += 1
                self._stop_timed_out = False
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="dryad-serve-batcher")
                self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        # keep _thread set until the worker is joined: clearing it first
        # would let a concurrent submit's start() spawn a SECOND worker
        # (two dispatchers racing on the cache) while this one drains
        with self._lock:
            thread = self._thread
            token = _StopToken(self._gen)
        if thread is None:
            return
        if thread.is_alive():
            # bounded put: with the queue full AND the worker stuck in a
            # stalled dispatch, a blocking put would wedge stop() before
            # its join timeout could ever apply; on Full we fall through
            # to the timed-out bookkeeping and a later stop() retries
            deadline = time.monotonic() + timeout
            try:
                self._q.put(token, timeout=timeout)
            except queue.Full:
                pass
            thread.join(max(0.0, deadline - time.monotonic()))
        with self._lock:
            # clear the handle ONLY once the worker is really dead: on a
            # join() timeout (worker stuck in a stalled device predict) a
            # cleared handle would let the next start() race a second
            # collector onto the same queue — the r8-flagged edge, pinned
            # by test_stop_timeout_keeps_stuck_worker_handle
            if self._thread is thread and not thread.is_alive():
                self._thread = None
                self._stop_timed_out = False
            elif thread.is_alive():
                # join timed out: remember it so a LATER start() may cancel
                # the still-queued token (restart-after-stuck-stop)
                self._stop_timed_out = True

    # ---- request path ------------------------------------------------------
    def submit(self, request: Request,
               timeout: Optional[float] = None) -> np.ndarray:
        """Enqueue, wait for the coalesced dispatch, return this request's
        slice of the results.  Raises ServeOverloaded / ServeTimeout, or
        re-raises the dispatch error."""
        t0 = time.perf_counter()
        if request.tctx is not None:
            request.tctx.t_submit = t0
        try:
            self._q.put_nowait(request)
        except queue.Full:
            if self.metrics is not None:
                self.metrics.record_rejected()
            raise ServeOverloaded(
                f"request queue full ({self._q.maxsize} waiting)") from None
        if self.metrics is not None:
            self.metrics.sample_queue_depth(self._q.qsize())
        if not request.event.wait(timeout):
            request.abandoned = True
            if self.metrics is not None:
                self.metrics.record_timeout()
            raise ServeTimeout(f"request timed out after {timeout}s")
        if request.error is not None:
            if self.metrics is not None:
                self.metrics.record_error(request.version)
            raise request.error
        if self.metrics is not None:
            self.metrics.record_request(request.rows.shape[0],
                                        time.perf_counter() - t0,
                                        request.version,
                                        priority=request.priority)
        return request.result

    # ---- worker ------------------------------------------------------------
    def _collect(self, first: Request,
                 downstream_full=None) -> tuple[list[Request], bool]:
        """Coalesce until the row cap or the max-wait deadline.

        ``downstream_full`` (pipeline mode) is demand-driven flow control:
        while the executor is backed up, shipping another batch would only
        park it in the handoff queue, so the deadline re-arms and the
        batch keeps coalescing — without this, a run-ahead collector opens
        batches into a momentarily empty queue and closes them on the
        deadline instead of the row cap, and the pipeline measures SLOWER
        than serial (observed; the bench compare pins the win now)."""
        batch, rows = [first], first.rows.shape[0]
        if first.tctx is not None:
            first.tctx.t_collect = time.perf_counter()
        deadline = time.perf_counter() + self.max_wait_s
        stopping = False
        while rows < self.max_batch_rows:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                if downstream_full is None or not downstream_full():
                    break
                remaining = self.max_wait_s     # executor backed up: re-arm
            try:
                nxt = self._q.get(timeout=remaining)
            except queue.Empty:
                if downstream_full is not None and downstream_full():
                    continue                    # still no demand downstream
                break
            if isinstance(nxt, _StopToken):
                if self._stop_live(nxt):
                    stopping = True
                    break
                continue        # stale: a start() since reinstated service
            if nxt.tctx is not None:
                nxt.tctx.t_collect = time.perf_counter()
            batch.append(nxt)
            rows += nxt.rows.shape[0]
        if self.metrics is not None:
            # the closing request may overshoot the cap (and one oversized
            # request opens a batch unconditionally): count such batches
            # as full rather than reporting a fill ratio above 1
            self.metrics.record_batch(rows, max(rows, self.max_batch_rows))
            self.metrics.sample_queue_depth(self._q.qsize())
        return batch, stopping

    @staticmethod
    def _stamp_execute(batch: list) -> None:
        """Mark the batch-assembly → predict boundary on every traced
        request (called just before dispatch/execute on the owning
        thread)."""
        t = time.perf_counter()
        for req in batch:
            if req.tctx is not None:
                req.tctx.t_execute = t

    def _deliver(self, batch: list, results) -> None:
        for req, out in zip(batch, results):
            # the dispatch may fail requests individually (e.g. one
            # group's model version was unloaded mid-queue) without
            # poisoning the rest of the batch
            if isinstance(out, BaseException):
                req.error = out
            else:
                req.result = out
            req.event.set()
        t_end = time.perf_counter()
        for req in batch:
            if req.tctx is not None:
                req.tctx.finish(t_end, self.metrics)

    @staticmethod
    def _fail(batch: list, error: BaseException) -> None:
        for req in batch:
            req.error = error
            req.event.set()

    def _stop_live(self, token: _StopToken) -> bool:
        with self._lock:
            return token.gen == self._gen

    def _run(self) -> None:
        if self.pipelined:
            self._run_pipeline()
        else:
            self._run_serial()

    def _run_serial(self) -> None:
        while True:
            item = self._q.get()
            if isinstance(item, _StopToken):
                if self._stop_live(item):
                    self._drain()
                    return
                continue        # stale: a start() since reinstated service
            with span("serve.collect"):
                batch, stopping = self._collect(item)
            try:
                self._stamp_execute(batch)
                with span("serve.dispatch"):
                    results = self._dispatch(batch)
                self._deliver(batch, results)
            except BaseException as e:  # noqa: BLE001 — delivered to callers
                self._fail(batch, e)
            if stopping:
                self._drain()
                return

    def _run_pipeline(self) -> None:
        # run-ahead cap: the executor holds one batch in flight and this
        # queue holds pipeline_depth - 1 more; collector blocks beyond that
        handoff: queue.Queue = queue.Queue(maxsize=self.pipeline_depth - 1)

        def executor() -> None:
            while True:
                item = handoff.get()
                if item is _STOP:
                    return
                batch, prepared = item
                try:
                    self._stamp_execute(batch)
                    with span("serve.execute"):
                        results = self._execute(prepared)
                    self._deliver(batch, results)
                except BaseException as e:  # noqa: BLE001 — to callers
                    self._fail(batch, e)

        ex = threading.Thread(target=executor, daemon=True,
                              name="dryad-serve-executor")
        ex.start()
        stopping = False
        while not stopping:
            item = self._q.get()
            if isinstance(item, _StopToken):
                if self._stop_live(item):
                    break
                continue        # stale: a start() since reinstated service
            with span("serve.collect"):
                batch, stopping = self._collect(item,
                                                downstream_full=handoff.full)
            try:
                with span("serve.prepare"):
                    prepared = self._prepare(batch)
            except BaseException as e:  # noqa: BLE001 — to callers
                self._fail(batch, e)
                continue
            handoff.put((batch, prepared))
        handoff.put(_STOP)
        ex.join()
        self._drain()

    def _drain(self) -> None:
        """Fail anything enqueued behind the stop sentinel — a caller with
        no timeout would otherwise wait forever on a dead worker."""
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                return
            if isinstance(req, _StopToken):
                continue
            req.error = ServeOverloaded("batcher stopped")
            req.event.set()
