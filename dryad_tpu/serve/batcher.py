"""Micro-batching queue: coalesce concurrent requests into one dispatch.

A single worker thread drains a bounded queue.  The first dequeued
request opens a batch and starts a max-wait deadline clock; requests
keep joining until the row cap is reached or the deadline expires, then
the whole batch goes to the device in one dispatch.  Under load batches
fill instantly (the deadline never waits); when idle a lone request pays
at most ``max_wait_ms`` of extra latency.

Backpressure is the bounded queue itself: when it is full, ``submit``
fails fast with ``ServeOverloaded`` instead of letting latency grow
without bound.  Each caller may also bound its own wait with a
per-request timeout (``ServeTimeout``); an abandoned request's result is
simply dropped when the batch completes.

Results come back bitwise equal to solo predicts: the dispatch function
slices the coalesced output per request, and every predict stage is
per-row (see cache.py).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

import numpy as np


class ServeOverloaded(RuntimeError):
    """The request queue is full — shed load upstream."""


class ServeTimeout(TimeoutError):
    """The per-request timeout expired before the batch completed."""


class Request:
    """One submitted predict request; ``rows`` is the pre-binned matrix."""

    __slots__ = ("rows", "version", "raw_score", "event", "result", "error",
                 "abandoned")

    def __init__(self, rows: np.ndarray, version: Optional[int] = None,
                 raw_score: bool = False):
        self.rows = rows
        self.version = version
        self.raw_score = raw_score
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.abandoned = False


_STOP = object()


class MicroBatcher:
    """Bounded-queue request coalescer around a batch dispatch function.

    ``dispatch(batch)`` receives the list of coalesced ``Request``s and
    returns one result per request, in order.
    """

    def __init__(self, dispatch, *, max_batch_rows: int = 4096,
                 max_wait_ms: float = 2.0, queue_size: int = 256,
                 metrics=None):
        self._dispatch = dispatch
        self.max_batch_rows = int(max_batch_rows)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.metrics = metrics
        self._q: queue.Queue = queue.Queue(maxsize=int(queue_size))
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # ---- lifecycle ---------------------------------------------------------
    def start(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="dryad-serve-batcher")
                self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        # keep _thread set until the worker is joined: clearing it first
        # would let a concurrent submit's start() spawn a SECOND worker
        # (two dispatchers racing on the cache) while this one drains
        with self._lock:
            thread = self._thread
        if thread is None:
            return
        if thread.is_alive():
            self._q.put(_STOP)
            thread.join(timeout)
        with self._lock:
            if self._thread is thread:
                self._thread = None

    # ---- request path ------------------------------------------------------
    def submit(self, request: Request,
               timeout: Optional[float] = None) -> np.ndarray:
        """Enqueue, wait for the coalesced dispatch, return this request's
        slice of the results.  Raises ServeOverloaded / ServeTimeout, or
        re-raises the dispatch error."""
        t0 = time.perf_counter()
        try:
            self._q.put_nowait(request)
        except queue.Full:
            if self.metrics is not None:
                self.metrics.record_rejected()
            raise ServeOverloaded(
                f"request queue full ({self._q.maxsize} waiting)") from None
        if self.metrics is not None:
            self.metrics.sample_queue_depth(self._q.qsize())
        if not request.event.wait(timeout):
            request.abandoned = True
            if self.metrics is not None:
                self.metrics.record_timeout()
            raise ServeTimeout(f"request timed out after {timeout}s")
        if request.error is not None:
            if self.metrics is not None:
                self.metrics.record_error()
            raise request.error
        if self.metrics is not None:
            self.metrics.record_request(request.rows.shape[0],
                                        time.perf_counter() - t0)
        return request.result

    # ---- worker ------------------------------------------------------------
    def _collect(self, first: Request) -> tuple[list[Request], bool]:
        """Coalesce until the row cap or the max-wait deadline."""
        batch, rows = [first], first.rows.shape[0]
        deadline = time.perf_counter() + self.max_wait_s
        stopping = False
        while rows < self.max_batch_rows:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            try:
                nxt = self._q.get(timeout=remaining)
            except queue.Empty:
                break
            if nxt is _STOP:
                stopping = True
                break
            batch.append(nxt)
            rows += nxt.rows.shape[0]
        if self.metrics is not None:
            self.metrics.record_batch(rows, self.max_batch_rows)
            self.metrics.sample_queue_depth(self._q.qsize())
        return batch, stopping

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is _STOP:
                self._drain()
                return
            batch, stopping = self._collect(item)
            try:
                results = self._dispatch(batch)
                for req, out in zip(batch, results):
                    # the dispatch may fail requests individually (e.g. one
                    # group's model version was unloaded mid-queue) without
                    # poisoning the rest of the batch
                    if isinstance(out, BaseException):
                        req.error = out
                    else:
                        req.result = out
                    req.event.set()
            except BaseException as e:  # noqa: BLE001 — delivered to callers
                for req in batch:
                    req.error = e
                    req.event.set()
            if stopping:
                self._drain()
                return

    def _drain(self) -> None:
        """Fail anything enqueued behind the stop sentinel — a caller with
        no timeout would otherwise wait forever on a dead worker."""
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                return
            if req is _STOP:
                continue
            req.error = ServeOverloaded("batcher stopped")
            req.event.set()
