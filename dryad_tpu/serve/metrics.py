"""Serving metrics: thread-safe counters + a bounded latency reservoir.

One ``ServeMetrics`` instance is shared by the server, the micro-batcher,
and the compiled-predict cache; ``snapshot()`` is the stats API the CLI
and the HTTP ``/stats`` endpoint expose.  Latency percentiles come from a
fixed-size reservoir of the most recent request latencies (a deque, not a
histogram) — exact over the window, O(window) only at snapshot time, and
free of bucket-boundary error at the tails we care about (p99).
"""

from __future__ import annotations

import threading
from collections import deque


class ServeMetrics:
    def __init__(self, latency_window: int = 4096):
        self._lock = threading.Lock()
        self._latencies = deque(maxlen=int(latency_window))
        self.requests = 0          # completed requests (incl. empty)
        self.rows = 0              # rows predicted across completed requests
        self.batches = 0           # device dispatches by the micro-batcher
        self.batch_rows = 0        # rows across those dispatches
        self.batch_capacity = 0    # Σ max_batch_rows across dispatches
        self.cache_hits = 0        # bucket already compiled/prepared
        self.cache_compiles = 0    # new (version, bucket) entries built
        self.timeouts = 0          # requests that gave up waiting
        self.rejected = 0          # requests refused by the bounded queue
        self.errors = 0            # requests that raised in dispatch
        self.queue_depth = 0       # last sampled queue depth
        self.queue_depth_peak = 0

    # ---- recording ---------------------------------------------------------
    def record_request(self, n_rows: int, latency_s: float) -> None:
        with self._lock:
            self.requests += 1
            self.rows += int(n_rows)
            self._latencies.append(float(latency_s))

    def record_batch(self, rows: int, capacity: int) -> None:
        with self._lock:
            self.batches += 1
            self.batch_rows += int(rows)
            self.batch_capacity += int(capacity)

    def record_cache(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self.cache_hits += 1
            else:
                self.cache_compiles += 1

    def record_timeout(self) -> None:
        with self._lock:
            self.timeouts += 1

    def record_rejected(self) -> None:
        with self._lock:
            self.rejected += 1

    def record_error(self) -> None:
        with self._lock:
            self.errors += 1

    def sample_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = int(depth)
            self.queue_depth_peak = max(self.queue_depth_peak, int(depth))

    # ---- snapshot ----------------------------------------------------------
    def snapshot(self) -> dict:
        """One consistent dict of everything — counters plus derived rates.
        Latency keys are milliseconds."""
        with self._lock:
            lat = sorted(self._latencies)

            def pct(p: float) -> float:
                if not lat:
                    return 0.0
                # nearest-rank on the reservoir
                idx = min(len(lat) - 1, max(0, int(round(p * (len(lat) - 1)))))
                return lat[idx] * 1e3

            return {
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "batch_rows": self.batch_rows,
                "batch_fill_ratio": (self.batch_rows / self.batch_capacity
                                     if self.batch_capacity else 0.0),
                "p50_ms": pct(0.50),
                "p99_ms": pct(0.99),
                "mean_ms": (sum(lat) / len(lat) * 1e3 if lat else 0.0),
                "cache_hits": self.cache_hits,
                "cache_compiles": self.cache_compiles,
                "timeouts": self.timeouts,
                "rejected": self.rejected,
                "errors": self.errors,
                "queue_depth": self.queue_depth,
                "queue_depth_peak": self.queue_depth_peak,
            }
