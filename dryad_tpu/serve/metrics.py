"""Serving metrics: thread-safe counters + mergeable latency histograms.

One ``ServeMetrics`` instance is shared by the server, the micro-batcher,
the compiled-predict cache, and the model registry; ``snapshot()`` is the
stats API the CLI and the HTTP ``/stats`` endpoint expose.  Latency
percentiles come from the fixed-log-bucket histogram family
(``obs.registry.LOG_BUCKETS``): O(1) observe, no unbounded sort at
snapshot time, and — the r17 point — EXACT count-merge across processes,
so the fleet router can aggregate per-replica percentiles into one
fleet-wide p99 instead of averaging unmergeable reservoirs.  (The old
sorted-reservoir path is gone: quantiles are now bucket-resolution,
~26% worst-case on the 10-per-decade scheme — the right trade for a
number that must compose across a fleet.)  The reservoir's RECENCY is
kept: local snapshot percentiles read a two-epoch rotating window of
roughly the most recent ``latency_window`` requests (``_WindowedHist``),
so a regression on a long-lived server still shows within one window —
only the shared-registry mirrors are cumulative.

Multi-model co-serving adds a per-model ledger: every counter that can be
attributed to a version (requests, rows, latencies, cache warmth,
evictions/re-stages) is ALSO recorded under that version, so operators
can see which resident model is earning its device memory.  The ledger
lives here, NOT on the registry entry — eviction drops a model's staged
arrays but must never drop its history (test-pinned).

Round 9: every recording is ALSO mirrored into the shared telemetry
registry (``dryad_tpu/obs``) as ``dryad_serve_*`` series, so serving
shows up on the unified ``/metrics``/``/stats`` pane next to training
and resilience.  The LOCAL fields stay authoritative for ``snapshot()``
— its shape is unchanged (test-pinned): the process-wide registry is
cumulative across server instances (Prometheus counter semantics), while
a ``ServeMetrics`` instance is per-server.  r17 adds the per-(priority,
stage) request-latency family ``dryad_request_latency_seconds`` — the
SAME name at router and replica, which is what makes the router's exact
fleet merge a label-join instead of a schema mapping."""

from __future__ import annotations

import threading
from typing import Optional

from dryad_tpu.obs.registry import (REQUEST_LATENCY, Registry,
                                    default_registry, hist_quantile,
                                    merge_hist_states, new_hist_state,
                                    observe_log_state)

__all__ = ["ModelStats", "ServeMetrics", "REQUEST_LATENCY"]


class _WindowedHist:
    """Two-epoch rotating log-bucket histogram: percentiles over the
    most recent ~``window`` observations (between window/2 and window —
    the current epoch plus the previous full one), O(1) observe.  This
    preserves the pre-r17 reservoir's RECENCY contract — a latency
    regression shows in snapshot percentiles within one window, however
    long the process has run — without its unbounded sort.  The shared
    registry mirrors stay cumulative (Prometheus semantics); only the
    local snapshot reads this.  Guarded by the owning ServeMetrics
    lock, exactly like the deques it replaces."""

    __slots__ = ("half", "cur", "prev")

    def __init__(self, window: int):
        self.half = max(1, int(window) // 2)
        self.cur = new_hist_state()
        self.prev = None

    def observe(self, value: float) -> None:
        observe_log_state(self.cur, value)
        if self.cur[2] >= self.half:
            self.prev, self.cur = self.cur, new_hist_state()

    def state(self) -> tuple:
        if self.prev is None:
            return tuple(self.cur)
        return merge_hist_states([self.prev, self.cur])


def _pcts(state) -> tuple:
    """(p50_ms, p99_ms, mean_ms) from a log-hist state (mean is exact
    over the state's observations)."""
    counts, total, n = state
    if not n:
        return 0.0, 0.0, 0.0
    return (hist_quantile(counts, 0.50) * 1e3,
            hist_quantile(counts, 0.99) * 1e3,
            total / n * 1e3)


class ModelStats:
    """Per-version slice of the serving counters (guarded by the owning
    ServeMetrics lock; never touched directly by callers)."""

    __slots__ = ("requests", "rows", "lat_hist", "cache_hits",
                 "cache_compiles", "evictions", "restages", "errors")

    def __init__(self, latency_window: int = 512):
        self.requests = 0
        self.rows = 0
        self.lat_hist = _WindowedHist(latency_window)
        self.cache_hits = 0
        self.cache_compiles = 0
        self.evictions = 0
        self.restages = 0
        self.errors = 0

    def snapshot(self) -> dict:
        p50, p99, _ = _pcts(self.lat_hist.state())
        return {
            "requests": self.requests,
            "rows": self.rows,
            "p50_ms": p50,
            "p99_ms": p99,
            "cache_hits": self.cache_hits,
            "cache_compiles": self.cache_compiles,
            "evictions": self.evictions,
            "restages": self.restages,
            "errors": self.errors,
        }


class ServeMetrics:
    """All local counters and the reservoirs live under the one ``_lock``
    (declared below); record methods take it once per event and snapshot
    takes it once for the whole consistent view.  The ``_obs_*`` mirror
    handles are immutable after construction and record into the shared
    registry's own per-family locks OUTSIDE ours — the mirror happens
    after ``_lock`` is released, so the two lock domains never nest.
    ``_model_locked`` is the called-with-the-lock-held helper idiom the
    guarded-by lint recognizes (and checks at its call sites)."""

    GUARDED_BY = {
        "_lat_hist": "_lock", "_models": "_lock",
        "requests": "_lock", "rows": "_lock",
        "batches": "_lock", "batch_rows": "_lock",
        "batch_capacity": "_lock",
        "cache_hits": "_lock", "cache_compiles": "_lock",
        "timeouts": "_lock", "rejected": "_lock", "errors": "_lock",
        "evictions": "_lock", "restages": "_lock",
        "queue_depth": "_lock", "queue_depth_peak": "_lock",
    }

    def __init__(self, latency_window: int = 4096,
                 registry: Optional[Registry] = None):
        # latency_window keeps its pre-r17 meaning: local snapshot
        # percentiles cover roughly the most recent `latency_window`
        # requests (the two-epoch rotation above), so regressions show
        # within one window regardless of process age
        self._lock = threading.Lock()
        # shared-registry mirror: bound series handles so the hot path is
        # one enabled-check per record when obs is disabled
        reg = registry if registry is not None else default_registry()
        self._obs = reg
        self._obs_requests = reg.counter(
            "dryad_serve_requests_total", "Completed predict requests")
        self._obs_rows = reg.counter(
            "dryad_serve_rows_total", "Rows predicted")
        # per-version breakdowns live in their OWN families: a labeled
        # series inside the totals family would make family-level PromQL
        # (sum(dryad_serve_requests_total)) double-count every request
        self._obs_requests_v = reg.counter(
            "dryad_serve_requests_by_version_total",
            "Completed predict requests by model version")
        self._obs_rows_v = reg.counter(
            "dryad_serve_rows_by_version_total",
            "Rows predicted by model version")
        self._obs_errors_v = reg.counter(
            "dryad_serve_errors_by_version_total",
            "Dispatch errors by model version")
        self._obs_latency = reg.log_histogram(
            "dryad_serve_request_latency_seconds",
            "End-to-end request latency")
        # per-(priority, stage) request latency — the family the fleet
        # router merges exactly across replicas (stages: queue_wait /
        # batch_assembly / predict / total at a replica, router at the
        # router); bound per-label handles are resolved lazily in
        # record_stage (label cardinality is tiny and bounded)
        self._obs_req_latency = reg.log_histogram(
            REQUEST_LATENCY,
            "Request latency by priority class and pipeline stage")
        self._obs_batches = reg.counter(
            "dryad_serve_batches_total", "Device dispatches")
        self._obs_batch_rows = reg.counter(
            "dryad_serve_batch_rows_total", "Rows across dispatches")
        self._obs_cache_hits = reg.counter(
            "dryad_serve_cache_hits_total", "Warm compiled-bucket hits")
        self._obs_cache_compiles = reg.counter(
            "dryad_serve_cache_compiles_total", "New compiled entries")
        self._obs_timeouts = reg.counter(
            "dryad_serve_timeouts_total", "Requests that gave up waiting")
        self._obs_rejected = reg.counter(
            "dryad_serve_rejected_total", "Requests shed by backpressure")
        self._obs_errors = reg.counter(
            "dryad_serve_errors_total", "Requests that raised in dispatch")
        self._obs_evictions = reg.counter(
            "dryad_serve_evictions_total", "Staged models evicted")
        self._obs_restages = reg.counter(
            "dryad_serve_restages_total", "Evicted models re-staged")
        self._obs_queue_depth = reg.gauge(
            "dryad_serve_queue_depth", "Last sampled request-queue depth")
        self._lat_hist = _WindowedHist(latency_window)
        # per-model windows track the configured window but are capped
        # at 512 each — the model count is unbounded, the global window
        # is not (the pre-r17 reservoir's own rule)
        self._model_window = min(512, int(latency_window))
        self._models: dict[int, ModelStats] = {}
        self.requests = 0          # completed requests (incl. empty)
        self.rows = 0              # rows predicted across completed requests
        self.batches = 0           # device dispatches by the micro-batcher
        self.batch_rows = 0        # rows across those dispatches
        self.batch_capacity = 0    # Σ max_batch_rows across dispatches
        self.cache_hits = 0        # bucket already compiled/prepared
        self.cache_compiles = 0    # new (version, bucket, shards) entries built
        self.timeouts = 0          # requests that gave up waiting
        self.rejected = 0          # requests refused by the bounded queue
        self.errors = 0            # requests that raised in dispatch
        self.evictions = 0         # staged models dropped by the LRU budget
        self.restages = 0          # evicted models staged again on demand
        self.queue_depth = 0       # last sampled queue depth
        self.queue_depth_peak = 0

    def _model_locked(self, version: Optional[int]) -> Optional[ModelStats]:
        if version is None:
            return None
        ms = self._models.get(version)
        if ms is None:
            ms = self._models[version] = ModelStats(self._model_window)
        return ms

    @property
    def obs_enabled(self) -> bool:
        """Whether the shared registry records (the request path's gate
        for allocating per-request trace context — serve/batcher.py)."""
        return self._obs.enabled

    @property
    def obs_registry(self) -> Registry:
        """The registry this instance mirrors into — RequestTrace.finish
        emits its stage spans there too, so the tctx-allocation gate,
        the stage histograms, and the span series all agree on ONE
        registry (a private test registry included)."""
        return self._obs

    # ---- recording ---------------------------------------------------------
    def record_request(self, n_rows: int, latency_s: float,
                       version: Optional[int] = None,
                       priority: Optional[str] = None) -> None:
        with self._lock:
            self.requests += 1
            self.rows += int(n_rows)
            self._lat_hist.observe(float(latency_s))
            ms = self._model_locked(version)
            if ms is not None:
                ms.requests += 1
                ms.rows += int(n_rows)
                ms.lat_hist.observe(float(latency_s))
        if self._obs.enabled:
            self._obs_requests.inc()
            self._obs_rows.inc(int(n_rows))
            self._obs_latency.observe(float(latency_s))
            self._obs_req_latency.labels(
                priority=priority or "interactive",
                stage="total").observe(float(latency_s))
            if version is not None:
                self._obs_requests_v.labels(version=version).inc()
                self._obs_rows_v.labels(version=version).inc(int(n_rows))

    def record_stage(self, stage: str, seconds: float,
                     priority: Optional[str] = None) -> None:
        """One pipeline-stage latency observation into the mergeable
        per-(priority, stage) family (registry-only — stages have no
        local ledger).  First action is the enabled check: the disabled
        path allocates nothing."""
        if self._obs.enabled:
            self._obs_req_latency.labels(
                priority=priority or "interactive",
                stage=stage).observe(float(seconds))

    def record_batch(self, rows: int, capacity: int) -> None:
        with self._lock:
            self.batches += 1
            self.batch_rows += int(rows)
            self.batch_capacity += int(capacity)
        if self._obs.enabled:
            self._obs_batches.inc()
            self._obs_batch_rows.inc(int(rows))

    def record_cache(self, hit: bool, version: Optional[int] = None) -> None:
        with self._lock:
            ms = self._model_locked(version)
            if hit:
                self.cache_hits += 1
                if ms is not None:
                    ms.cache_hits += 1
            else:
                self.cache_compiles += 1
                if ms is not None:
                    ms.cache_compiles += 1
        (self._obs_cache_hits if hit else self._obs_cache_compiles).inc()

    def record_eviction(self, version: Optional[int] = None) -> None:
        with self._lock:
            self.evictions += 1
            ms = self._model_locked(version)
            if ms is not None:
                ms.evictions += 1
        self._obs_evictions.inc()

    def record_restage(self, version: Optional[int] = None) -> None:
        with self._lock:
            self.restages += 1
            ms = self._model_locked(version)
            if ms is not None:
                ms.restages += 1
        self._obs_restages.inc()

    def record_timeout(self) -> None:
        with self._lock:
            self.timeouts += 1
        self._obs_timeouts.inc()

    def record_rejected(self) -> None:
        with self._lock:
            self.rejected += 1
        self._obs_rejected.inc()

    def record_error(self, version: Optional[int] = None) -> None:
        with self._lock:
            self.errors += 1
            ms = self._model_locked(version)
            if ms is not None:
                ms.errors += 1
        if self._obs.enabled:
            self._obs_errors.inc()
            if version is not None:
                self._obs_errors_v.labels(version=version).inc()

    def sample_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = int(depth)
            self.queue_depth_peak = max(self.queue_depth_peak, int(depth))
        self._obs_queue_depth.set(int(depth))

    # ---- snapshot ----------------------------------------------------------
    def snapshot(self) -> dict:
        """One consistent dict of everything — counters plus derived rates.
        Latency keys are milliseconds; ``models`` maps version → its slice."""
        with self._lock:
            p50, p99, mean = _pcts(self._lat_hist.state())
            return {
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "batch_rows": self.batch_rows,
                "batch_fill_ratio": (self.batch_rows / self.batch_capacity
                                     if self.batch_capacity else 0.0),
                "p50_ms": p50,
                "p99_ms": p99,
                "mean_ms": mean,
                "cache_hits": self.cache_hits,
                "cache_compiles": self.cache_compiles,
                "timeouts": self.timeouts,
                "rejected": self.rejected,
                "errors": self.errors,
                "evictions": self.evictions,
                "restages": self.restages,
                "queue_depth": self.queue_depth,
                "queue_depth_peak": self.queue_depth_peak,
                "models": {v: ms.snapshot()
                           for v, ms in sorted(self._models.items())},
            }
