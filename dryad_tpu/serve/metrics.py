"""Serving metrics: thread-safe counters + a bounded latency reservoir.

One ``ServeMetrics`` instance is shared by the server, the micro-batcher,
the compiled-predict cache, and the model registry; ``snapshot()`` is the
stats API the CLI and the HTTP ``/stats`` endpoint expose.  Latency
percentiles come from a fixed-size reservoir of the most recent request
latencies (a deque, not a histogram) — exact over the window, O(window)
only at snapshot time, and free of bucket-boundary error at the tails we
care about (p99).

Multi-model co-serving adds a per-model ledger: every counter that can be
attributed to a version (requests, rows, latencies, cache warmth,
evictions/re-stages) is ALSO recorded under that version, so operators
can see which resident model is earning its device memory.  The ledger
lives here, NOT on the registry entry — eviction drops a model's staged
arrays but must never drop its history (test-pinned)."""

from __future__ import annotations

import threading
from collections import deque
from typing import Optional


def _pct(lat: list, p: float) -> float:
    if not lat:
        return 0.0
    # nearest-rank on the reservoir
    idx = min(len(lat) - 1, max(0, int(round(p * (len(lat) - 1)))))
    return lat[idx] * 1e3


class ModelStats:
    """Per-version slice of the serving counters (guarded by the owning
    ServeMetrics lock; never touched directly by callers)."""

    __slots__ = ("requests", "rows", "latencies", "cache_hits",
                 "cache_compiles", "evictions", "restages", "errors")

    def __init__(self, latency_window: int = 512):
        self.requests = 0
        self.rows = 0
        self.latencies = deque(maxlen=int(latency_window))
        self.cache_hits = 0
        self.cache_compiles = 0
        self.evictions = 0
        self.restages = 0
        self.errors = 0

    def snapshot(self) -> dict:
        lat = sorted(self.latencies)
        return {
            "requests": self.requests,
            "rows": self.rows,
            "p50_ms": _pct(lat, 0.50),
            "p99_ms": _pct(lat, 0.99),
            "cache_hits": self.cache_hits,
            "cache_compiles": self.cache_compiles,
            "evictions": self.evictions,
            "restages": self.restages,
            "errors": self.errors,
        }


class ServeMetrics:
    def __init__(self, latency_window: int = 4096):
        self._lock = threading.Lock()
        self._latencies = deque(maxlen=int(latency_window))
        # per-model reservoirs track the configured window but are capped
        # at 512 each — the model count is unbounded, the global window
        # is not
        self._model_window = min(512, int(latency_window))
        self._models: dict[int, ModelStats] = {}
        self.requests = 0          # completed requests (incl. empty)
        self.rows = 0              # rows predicted across completed requests
        self.batches = 0           # device dispatches by the micro-batcher
        self.batch_rows = 0        # rows across those dispatches
        self.batch_capacity = 0    # Σ max_batch_rows across dispatches
        self.cache_hits = 0        # bucket already compiled/prepared
        self.cache_compiles = 0    # new (version, bucket, shards) entries built
        self.timeouts = 0          # requests that gave up waiting
        self.rejected = 0          # requests refused by the bounded queue
        self.errors = 0            # requests that raised in dispatch
        self.evictions = 0         # staged models dropped by the LRU budget
        self.restages = 0          # evicted models staged again on demand
        self.queue_depth = 0       # last sampled queue depth
        self.queue_depth_peak = 0

    def _model(self, version: Optional[int]) -> Optional[ModelStats]:
        if version is None:
            return None
        ms = self._models.get(version)
        if ms is None:
            ms = self._models[version] = ModelStats(self._model_window)
        return ms

    # ---- recording ---------------------------------------------------------
    def record_request(self, n_rows: int, latency_s: float,
                       version: Optional[int] = None) -> None:
        with self._lock:
            self.requests += 1
            self.rows += int(n_rows)
            self._latencies.append(float(latency_s))
            ms = self._model(version)
            if ms is not None:
                ms.requests += 1
                ms.rows += int(n_rows)
                ms.latencies.append(float(latency_s))

    def record_batch(self, rows: int, capacity: int) -> None:
        with self._lock:
            self.batches += 1
            self.batch_rows += int(rows)
            self.batch_capacity += int(capacity)

    def record_cache(self, hit: bool, version: Optional[int] = None) -> None:
        with self._lock:
            ms = self._model(version)
            if hit:
                self.cache_hits += 1
                if ms is not None:
                    ms.cache_hits += 1
            else:
                self.cache_compiles += 1
                if ms is not None:
                    ms.cache_compiles += 1

    def record_eviction(self, version: Optional[int] = None) -> None:
        with self._lock:
            self.evictions += 1
            ms = self._model(version)
            if ms is not None:
                ms.evictions += 1

    def record_restage(self, version: Optional[int] = None) -> None:
        with self._lock:
            self.restages += 1
            ms = self._model(version)
            if ms is not None:
                ms.restages += 1

    def record_timeout(self) -> None:
        with self._lock:
            self.timeouts += 1

    def record_rejected(self) -> None:
        with self._lock:
            self.rejected += 1

    def record_error(self, version: Optional[int] = None) -> None:
        with self._lock:
            self.errors += 1
            ms = self._model(version)
            if ms is not None:
                ms.errors += 1

    def sample_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = int(depth)
            self.queue_depth_peak = max(self.queue_depth_peak, int(depth))

    # ---- snapshot ----------------------------------------------------------
    def snapshot(self) -> dict:
        """One consistent dict of everything — counters plus derived rates.
        Latency keys are milliseconds; ``models`` maps version → its slice."""
        with self._lock:
            lat = sorted(self._latencies)
            return {
                "requests": self.requests,
                "rows": self.rows,
                "batches": self.batches,
                "batch_rows": self.batch_rows,
                "batch_fill_ratio": (self.batch_rows / self.batch_capacity
                                     if self.batch_capacity else 0.0),
                "p50_ms": _pct(lat, 0.50),
                "p99_ms": _pct(lat, 0.99),
                "mean_ms": (sum(lat) / len(lat) * 1e3 if lat else 0.0),
                "cache_hits": self.cache_hits,
                "cache_compiles": self.cache_compiles,
                "timeouts": self.timeouts,
                "rejected": self.rejected,
                "errors": self.errors,
                "evictions": self.evictions,
                "restages": self.restages,
                "queue_depth": self.queue_depth,
                "queue_depth_peak": self.queue_depth_peak,
                "models": {v: ms.snapshot()
                           for v, ms in sorted(self._models.items())},
            }
