"""Closed-loop serving benchmark (the engine behind scripts/bench_serve.py).

``clients`` threads each run a closed loop — submit a request of a
random size, wait for the answer, repeat — against one PredictServer,
so concurrency (and therefore batch fill) is controlled exactly.

Warmup touches EVERY bucket the cache can ever produce (cache.buckets()),
not just the request sizes: coalescing means batch totals land on
arbitrary buckets up to the row cap, so warming only the request sizes
would leave cold buckets for the measured phase.  Routing to the sharded
entry family is a pure function of the bucket, so the same warmup warms
both shard arms.  After that structural warmup, a warm cache can never
compile again — ``recompiles_after_warmup`` must be 0, and
tests/test_serve.py asserts it on a forced-CPU run (scripts/ci.sh smokes
it across the bucketed AND sharded arms).

Measurement discipline follows bench.py / CLAUDE.md: the closed loop
runs ``arms`` times and the report carries the per-arm spread
(max/min - 1) next to the headline rows/s — a spread over 5% means the
capture is suspect (host contention, cold cache, tunnel noise) and the
report says so (``suspect_capture``) instead of letting a noisy point
masquerade as a trend.  ``run_bench_compare`` measures the overlapped
dispatch pipeline against the strictly serial loop on otherwise
identical servers and reports the speedup (ISSUE r7 acceptance:
pipeline ≥ 1.3× serial on CPU).
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

import numpy as np

from dryad_tpu.booster import Booster
from dryad_tpu.serve.server import PredictServer

SPREAD_SUSPECT = 0.05    # per-arm spread above this flags the capture


def run_bench(model, *, backend: str = "cpu", clients: int = 4,
              duration_s: float = 2.0, sizes: Sequence[int] = (1, 3, 9, 17, 40),
              max_batch_rows: int = 256, max_wait_ms: float = 1.0,
              queue_size: int = 1024, min_bucket: int = 8, seed: int = 0,
              pipeline_depth: int = 2, sharded="auto",
              sharded_threshold: Optional[int] = None, arms: int = 1,
              feature_pool: Optional[np.ndarray] = None,
              drift="auto", drift_window: int = 4096,
              verbose: bool = False) -> dict:
    """Run the closed loop; returns the stats snapshot plus bench fields
    (throughput, per-arm spread, recompiles_after_warmup).  ``model`` is a
    Booster or a model path (binary or text)."""
    booster = model if isinstance(model, Booster) else Booster.load_any(model)
    server = PredictServer(backend=backend, max_batch_rows=max_batch_rows,
                           max_wait_ms=max_wait_ms, queue_size=queue_size,
                           min_bucket=min_bucket,
                           pipeline_depth=pipeline_depth, sharded=sharded,
                           sharded_threshold=sharded_threshold,
                           drift=drift, drift_window=drift_window)
    server.registry.add(booster)
    rng = np.random.default_rng(seed)
    if feature_pool is None:
        feature_pool = rng.standard_normal(
            (max(int(max_batch_rows), 512), booster.mapper.num_features)
        ).astype(np.float32)
    pool_n = feature_pool.shape[0]
    sizes = [int(s) for s in sizes if 0 < int(s) <= pool_n]

    with server:
        # ---- structural warmup: one request per possible bucket ------------
        for b in server.cache.buckets():
            server.predict(feature_pool[:min(b, pool_n)])
        # arm the recompile tripwire: from here on a cold compiled-entry
        # key is not just counted in recompiles_after_warmup below but
        # fires dryad_recompile_unexpected_total and degrades /healthz
        server.warmup_complete()
        warm = server.stats()
        compiles_at_warmup = warm["cache_compiles"]
        if verbose:
            print(f"warmed {warm['compiled_buckets']} buckets "
                  f"({compiles_at_warmup} compiles, "
                  f"{server.cache.n_shards} shards, "
                  f"threshold {server.cache.sharded_threshold})")

        # ---- measured closed loop, `arms` repetitions ----------------------
        arm_reqs, arm_rows, arm_rows_per_s, arm_reqs_per_s = [], [], [], []
        for arm in range(max(1, int(arms))):
            counts = [0] * clients
            row_counts = [0] * clients
            barrier = threading.Barrier(clients + 1)
            # the deadline must be set BEFORE the barrier releases anyone,
            # or a fast client could read it unset and exit with zero
            # requests
            stop_at = [float("inf")]

            def client(ci: int) -> None:
                crng = np.random.default_rng(seed + 1000 * (arm + 1) + ci)
                barrier.wait()
                while time.perf_counter() < stop_at[0]:
                    n = int(crng.choice(sizes))
                    start = int(crng.integers(0, pool_n - n + 1))
                    server.predict(feature_pool[start:start + n])
                    counts[ci] += 1
                    row_counts[ci] += n

            threads = [threading.Thread(target=client, args=(ci,),
                                        daemon=True)
                       for ci in range(clients)]
            for t in threads:
                t.start()
            stop_at[0] = time.perf_counter() + float(duration_s)
            barrier.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t0
            arm_reqs.append(sum(counts))
            arm_rows.append(sum(row_counts))
            # one denominator for BOTH rates: the measured elapsed, which
            # includes in-flight batches completing past the deadline
            arm_rows_per_s.append(sum(row_counts) / elapsed
                                  if elapsed > 0 else 0.0)
            arm_reqs_per_s.append(sum(counts) / elapsed
                                  if elapsed > 0 else 0.0)
        snap = server.stats()

    spread = (max(arm_rows_per_s) / min(arm_rows_per_s) - 1
              if len(arm_rows_per_s) > 1 and min(arm_rows_per_s) > 0 else 0.0)
    snap["bench_clients"] = clients
    snap["bench_arms"] = len(arm_rows_per_s)
    snap["bench_requests"] = sum(arm_reqs)
    snap["bench_rows"] = sum(arm_rows)
    snap["requests_per_s"] = float(np.mean(arm_reqs_per_s))
    snap["rows_per_s"] = float(np.mean(arm_rows_per_s))
    snap["rows_per_s_arms"] = [round(r, 1) for r in arm_rows_per_s]
    snap["spread_rows_per_s"] = round(spread, 3)
    snap["suspect_capture"] = bool(spread > SPREAD_SUSPECT)
    snap["recompiles_after_warmup"] = (snap["cache_compiles"]
                                       - compiles_at_warmup)
    return snap


def summary_line(report: dict, label: str = "serve") -> dict:
    """The one-line JSON summary (bench.py's format: flat dict, printed as
    a single ``json.dumps`` line) distilled from a full report."""
    return {
        "bench": label,
        "rows_per_s": round(report["rows_per_s"], 1),
        "requests_per_s": round(report["requests_per_s"], 1),
        "p50_ms": round(report["p50_ms"], 3),
        "p99_ms": round(report["p99_ms"], 3),
        "batch_fill_ratio": round(report["batch_fill_ratio"], 3),
        "recompiles_after_warmup": report["recompiles_after_warmup"],
        "spread_rows_per_s": report["spread_rows_per_s"],
        "suspect_capture": report["suspect_capture"],
        "pipeline_depth": report["pipeline_depth"],
        "mesh_shards": report["mesh_shards"],
    }


def run_bench_drift(model, *, arms: int = 2, **kw) -> dict:
    """Drift-monitor overhead A/B (the obs_overhead_ms shape, r18): the
    SAME closed loop on two otherwise identical servers — drift
    monitoring on (model carrying a reference profile) vs off — reports
    ``drift_overhead_ms`` (per request), ``drift_overhead_pct`` (rows/s
    cost) and ``drift_overhead_spread`` (the max of both arms' per-arm
    spreads: a noisy capture vetoes the number, never fakes a verdict).
    The acceptance gate is <= 2% — the monitor is one vectorized
    bincount per batch, and a model-quality layer that taxes serving
    more than that would be disabled in anger."""
    booster = model if isinstance(model, Booster) else Booster.load_any(model)
    if getattr(booster, "profile", None) is None:
        # the arm must measure a LIVE monitor: synthesize a baseline over
        # a pool binned through the model's own mapper
        from dryad_tpu.data.profile import profile_from_binned

        rng = np.random.default_rng(kw.get("seed", 0))
        pool = rng.standard_normal(
            (2048, booster.mapper.num_features)).astype(np.float32)
        booster.profile = profile_from_binned(
            booster, booster.mapper.transform(pool))
    on = run_bench(booster, drift="auto", arms=arms, **kw)
    off = run_bench(booster, drift=False, arms=arms, **kw)
    if not on.get("drift"):
        raise RuntimeError("the instrumented arm never built a drift "
                           "monitor — the overhead A/B measured nothing")
    pct = (off["rows_per_s"] / on["rows_per_s"] - 1
           if on["rows_per_s"] > 0 else 0.0)
    ms = ((1.0 / on["requests_per_s"] - 1.0 / off["requests_per_s"]) * 1e3
          if on["requests_per_s"] > 0 and off["requests_per_s"] > 0 else 0.0)
    return {
        "drift_overhead_ms": round(ms, 4),
        "drift_overhead_pct": round(pct, 4),
        "drift_overhead_spread": round(max(on["spread_rows_per_s"],
                                           off["spread_rows_per_s"]), 3),
        "drift_rows_per_s_on": round(on["rows_per_s"], 1),
        "drift_rows_per_s_off": round(off["rows_per_s"], 1),
        "drift_windows": {m: d for m, d in on["drift"].items()},
    }


def run_bench_layout(model, *, arms: int = 2, backend: str = "tpu",
                     **kw) -> dict:
    """Packed-vs-legacy traversal layout A/B (r21): the SAME closed loop
    on two otherwise identical jax-backend servers, one forcing
    ``predict_layout='packed'`` (one node-word table gather per level),
    one ``'legacy'`` (the structure-of-arrays ~7).  The registry stages
    each arm's layout once at model add; everything downstream (cache
    programs, batcher dispatch, sharded family) inherits it, so the
    rows/s gap is the per-level gather saving measured end to end.
    ``layout_spread_*`` carries each arm's per-arm spread — the veto
    convention of every A/B here.  Defaults to the 'tpu' (jax) backend:
    the CPU predict path never stages device tables, so a cpu-backend
    A/B would measure nothing.  Forcing 'packed' raises on a model whose
    fields exceed the packed widths — a bench must not silently fall
    back to measuring legacy twice."""
    booster = model if isinstance(model, Booster) else Booster.load_any(model)
    orig = booster.params
    try:
        booster.params = orig.replace(predict_layout="packed")
        packed = run_bench(booster, backend=backend, arms=arms, **kw)
        booster.params = orig.replace(predict_layout="legacy")
        legacy = run_bench(booster, backend=backend, arms=arms, **kw)
    finally:
        booster.params = orig
    speedup = (packed["rows_per_s"] / legacy["rows_per_s"]
               if legacy["rows_per_s"] > 0 else 0.0)
    return {
        "layout_rows_per_s_packed": round(packed["rows_per_s"], 1),
        "layout_rows_per_s_legacy": round(legacy["rows_per_s"], 1),
        "predict_layout_speedup": round(speedup, 3),
        "layout_spread_packed": packed["spread_rows_per_s"],
        "layout_spread_legacy": legacy["spread_rows_per_s"],
        "layout_recompiles_after_warmup": (
            packed["recompiles_after_warmup"]
            + legacy["recompiles_after_warmup"]),
        "suspect_capture": (packed["suspect_capture"]
                            or legacy["suspect_capture"]),
    }


def run_bench_compare(model, *, pipeline_depth: int = 2, **kw) -> dict:
    """Pipeline-vs-serial A/B on otherwise identical servers: the serial
    arm pins ``pipeline_depth=1`` (the strictly sequential dispatch loop),
    the pipeline arm uses ``pipeline_depth``.  Returns both reports plus
    ``pipeline_speedup`` (rows/s ratio)."""
    serial = run_bench(model, pipeline_depth=1, **kw)
    pipeline = run_bench(model, pipeline_depth=pipeline_depth, **kw)
    speedup = (pipeline["rows_per_s"] / serial["rows_per_s"]
               if serial["rows_per_s"] > 0 else 0.0)
    return {
        "serial": serial,
        "pipeline": pipeline,
        "pipeline_speedup": round(speedup, 3),
        "recompiles_after_warmup": (serial["recompiles_after_warmup"]
                                    + pipeline["recompiles_after_warmup"]),
        "suspect_capture": (serial["suspect_capture"]
                            or pipeline["suspect_capture"]),
    }
