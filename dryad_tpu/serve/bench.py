"""Closed-loop serving benchmark (the engine behind scripts/bench_serve.py).

``clients`` threads each run a closed loop — submit a request of a
random size, wait for the answer, repeat — against one PredictServer,
so concurrency (and therefore batch fill) is controlled exactly.

Warmup touches EVERY bucket the cache can ever produce (cache.buckets()),
not just the request sizes: coalescing means batch totals land on
arbitrary buckets up to the row cap, so warming only the request sizes
would leave cold buckets for the measured phase.  After that structural
warmup, a warm cache can never compile again — ``recompiles_after_warmup``
must be 0, and tests/test_serve.py asserts it on a forced-CPU run.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

import numpy as np

from dryad_tpu.booster import Booster
from dryad_tpu.serve.server import PredictServer


def run_bench(model, *, backend: str = "cpu", clients: int = 4,
              duration_s: float = 2.0, sizes: Sequence[int] = (1, 3, 9, 17, 40),
              max_batch_rows: int = 256, max_wait_ms: float = 1.0,
              queue_size: int = 1024, min_bucket: int = 8, seed: int = 0,
              feature_pool: Optional[np.ndarray] = None,
              verbose: bool = False) -> dict:
    """Run the closed loop; returns the stats snapshot plus bench fields
    (throughput, recompiles_after_warmup).  ``model`` is a Booster or a
    model path (binary or text)."""
    booster = model if isinstance(model, Booster) else Booster.load_any(model)
    server = PredictServer(backend=backend, max_batch_rows=max_batch_rows,
                           max_wait_ms=max_wait_ms, queue_size=queue_size,
                           min_bucket=min_bucket)
    server.registry.add(booster)
    rng = np.random.default_rng(seed)
    if feature_pool is None:
        feature_pool = rng.standard_normal(
            (max(int(max_batch_rows), 512), booster.mapper.num_features)
        ).astype(np.float32)
    pool_n = feature_pool.shape[0]
    sizes = [int(s) for s in sizes if 0 < int(s) <= pool_n]

    with server:
        # ---- structural warmup: one request per possible bucket ------------
        for b in server.cache.buckets():
            server.predict(feature_pool[:min(b, pool_n)])
        warm = server.stats()
        compiles_at_warmup = warm["cache_compiles"]
        if verbose:
            print(f"warmed {warm['compiled_buckets']} buckets "
                  f"({compiles_at_warmup} compiles)")

        # ---- measured closed loop ------------------------------------------
        counts = [0] * clients
        row_counts = [0] * clients
        barrier = threading.Barrier(clients + 1)
        # the deadline must be set BEFORE the barrier releases anyone, or a
        # fast client could read it unset and exit with zero requests
        stop_at = [float("inf")]

        def client(ci: int) -> None:
            crng = np.random.default_rng(seed + 1000 + ci)
            barrier.wait()
            while time.perf_counter() < stop_at[0]:
                n = int(crng.choice(sizes))
                start = int(crng.integers(0, pool_n - n + 1))
                server.predict(feature_pool[start:start + n])
                counts[ci] += 1
                row_counts[ci] += n

        threads = [threading.Thread(target=client, args=(ci,), daemon=True)
                   for ci in range(clients)]
        for t in threads:
            t.start()
        stop_at[0] = time.perf_counter() + float(duration_s)
        barrier.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        snap = server.stats()

    snap["bench_clients"] = clients
    snap["bench_elapsed_s"] = elapsed
    snap["bench_requests"] = sum(counts)
    snap["bench_rows"] = sum(row_counts)
    snap["requests_per_s"] = sum(counts) / elapsed if elapsed > 0 else 0.0
    snap["rows_per_s"] = sum(row_counts) / elapsed if elapsed > 0 else 0.0
    snap["recompiles_after_warmup"] = (snap["cache_compiles"]
                                       - compiles_at_warmup)
    return snap
