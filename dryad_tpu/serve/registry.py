"""Model registry: versioned boosters with hot-swap, rollback, names,
and a device-memory budget with LRU eviction of staged trees.

Every loaded model gets a monotonically increasing integer version.  One
version is *active* (the default for requests that don't pin a version);
``activate`` hot-swaps it and records the previous active version on a
history stack so ``rollback`` is one call.  A model may also carry a
``name`` — a routing alias for multi-model co-serving ("fraud",
"ranker-v2"); re-adding under the same name repoints the alias, and
requests address either a pinned version or a name.  In-flight requests
resolve their version at submit time, so a swap never changes a request
that is already queued.

An entry lazily stages its tree tables for the device predict path
(``engine.predict.stage_trees``) and keeps them device-resident across
requests — the staged arrays are uploaded once per (version, process),
then passed as *arguments* to the jitted accumulate (never closed over:
remote compile rejects large jit constants, see CLAUDE.md).  For the
sharded predict family the tables are replicated over the mesh once at
stage time (``engine.distributed.replicate``) so per-dispatch transfers
never happen.

Co-serving many models cannot hold them ALL resident: ``budget_bytes``
bounds the summed staged-table footprint, and crossing it evicts the
least-recently-used staged entries.  Eviction drops ONLY the staged /
device arrays — the booster, the version, its aliases, and its metrics
history all survive, and the next request against an evicted version
transparently re-stages it (staging is lazy anyway).  The active version
and the entry that just staged are pinned, so the budget is best-effort:
it can be exceeded transiently when everything resident is pinned."""

from __future__ import annotations

import threading
from typing import Optional

from dryad_tpu.booster import Booster


class ModelEntry:
    """A registered model plus its lazily staged predict state.

    ``_lock`` guards the staging state (declared below); ``version``/
    ``booster``/``name`` are immutable after construction, ``last_used``
    is written by the REGISTRY under ITS lock (the registry's tick), and
    ``closed`` is flipped once by ``ModelRegistry.unload`` and only ever
    read under this lock.  The lock is never held across the registry
    lock — the eviction path deliberately picks victims under the
    registry lock and evicts them OUTSIDE it (see ``_on_staged``), which
    is why the lock-order goldens commit no edge between the two."""

    GUARDED_BY = {"_staged": "_lock", "_device": "_lock",
                  "_staged_bytes": "_lock", "_stage_count": "_lock",
                  "closed": "_lock"}

    def __init__(self, version: int, booster: Booster, path: Optional[str] = None,
                 num_iteration: Optional[int] = None,
                 name: Optional[str] = None, registry=None):
        self.version = int(version)
        self.booster = booster
        self.path = path
        self.name = name
        self.num_iteration = num_iteration
        self.last_used = 0        # registry tick; LRU eviction order
        self.closed = False       # set by unload: staging is over forever
        self._registry = registry
        self._lock = threading.Lock()
        self._staged = None       # (trees_np, init_np, n_iter)
        self._device = {}         # mesh (or None) → (trees_dev, init_dev)
        self._staged_bytes = 0
        self._stage_count = 0     # >1 means the entry was re-staged post-evict

    @property
    def num_outputs(self) -> int:
        return self.booster.num_outputs

    @property
    def is_staged(self) -> bool:
        with self._lock:
            return self._staged is not None

    @property
    def staged_layout(self) -> Optional[str]:
        """Traversal table layout of the staged predict state (r21):
        ``"packed"`` (node-word limb table) or ``"legacy"`` — None while
        nothing is staged.  Resolved once at ``stage_trees`` time from the
        model's ``predict_layout`` param; every downstream consumer
        (cache programs, sharded family, fleet replicas) inherits the
        staged dict, so this is THE layout the whole serve path runs."""
        with self._lock:
            if self._staged is None:
                return None
            from dryad_tpu.engine.predict import staged_layout

            return staged_layout(self._staged[0])

    @property
    def staged_bytes(self) -> int:
        """The budget's accounting unit: the host staged tables plus one
        mirror per device-state family built so far (a model warm on BOTH
        the single-device and the sharded family holds two independent
        device-0 copies).  Approximate by design — device copies built
        after the triggering stage event are only counted at the NEXT
        stage event — the budget is best-effort, not a hard cap."""
        with self._lock:
            if self._staged is None:
                return 0
            return self._staged_bytes * (1 + len(self._device))

    def staged(self):
        """(trees, init, n_iter) reshaped numpy tables, built once (again
        after an eviction); notifies the registry so the budget can react."""
        notify = restage = False
        with self._lock:
            if self.closed:
                # an unloaded entry must never re-stage (a stale compiled
                # closure calling in would rebuild arrays nothing can free)
                raise KeyError(
                    f"model version {self.version} is not loaded")
            if self._staged is None:
                from dryad_tpu.engine.predict import stage_trees

                self._staged = stage_trees(self.booster, self.num_iteration)
                trees_np, init_np, _ = self._staged
                self._staged_bytes = (sum(v.nbytes for v in trees_np.values())
                                      + init_np.nbytes)
                self._stage_count += 1
                notify = True
                restage = self._stage_count > 1
            staged = self._staged
        if notify and self._registry is not None:
            self._registry._on_staged(self, restage=restage)
        return staged

    def device_state(self, mesh=None):
        """Device-resident (trees, init) for the jit predict path; uploaded
        once and reused by every bucket's compiled program.  ``mesh`` keys
        the placement: None is the plain single-device upload, a Mesh gets
        the tables replicated over it for the shard_map family."""
        while True:
            trees_np, init_np, _ = self.staged()
            with self._lock:
                if self._staged is None:
                    # a concurrent budget eviction fired between staged()
                    # and here; caching device copies now would leave them
                    # resident but invisible to the budget accounting —
                    # re-stage and retry instead
                    continue
                return self._device_locked(mesh, trees_np, init_np)

    def _device_locked(self, mesh, trees_np, init_np):
        state = self._device.get(mesh)
        if state is None:
            import jax

            if mesh is not None:
                from dryad_tpu.engine.distributed import replicate

                state = (replicate(mesh, trees_np),
                         replicate(mesh, init_np))
            else:
                state = (
                    {k: jax.device_put(v) for k, v in trees_np.items()},
                    jax.device_put(init_np),
                )
            self._device[mesh] = state
        return state

    def evict_staged(self) -> int:
        """Drop the staged + device arrays (model/stats stay); returns the
        host bytes released.  The next ``staged()`` rebuilds lazily."""
        with self._lock:
            if self._staged is None:
                return 0
            freed = self._staged_bytes
            self._staged = None
            self._device = {}
            self._staged_bytes = 0
            return freed


class ModelRegistry:
    """The version/alias/active bookkeeping lives under ``_lock``
    (declared below).  The lock is held only for dict/stack updates —
    never across staging, eviction, or any entry-lock acquisition:
    ``_on_staged`` chooses victims under this lock but calls their
    ``evict_staged()`` (which takes each ENTRY's lock) after releasing
    it, the inversion-avoidance rule its docstring records."""

    GUARDED_BY = {"_models": "_lock", "_aliases": "_lock",
                  "_active": "_lock", "_history": "_lock",
                  "_next_version": "_lock", "_tick": "_lock"}

    def __init__(self, budget_bytes: Optional[int] = None, metrics=None):
        self._lock = threading.Lock()
        self._models: dict[int, ModelEntry] = {}
        self._aliases: dict[str, int] = {}
        self._active: Optional[int] = None
        self._history: list[int] = []   # previously active versions (for rollback)
        self._next_version = 1
        self._tick = 0
        self.budget_bytes = None if budget_bytes is None else int(budget_bytes)
        self.metrics = metrics

    # ---- loading -----------------------------------------------------------
    def load(self, path: str, *, activate: bool = True,
             num_iteration: Optional[int] = None,
             name: Optional[str] = None) -> int:
        """Register a model from disk — binary checkpoint or text dump,
        sniffed by content (Booster.load_any).  Returns its version."""
        return self.add(Booster.load_any(path), path=path, activate=activate,
                        num_iteration=num_iteration, name=name)

    def load_latest_checkpoint(self, directory: str, *, activate: bool = True,
                               num_iteration: Optional[int] = None,
                               name: Optional[str] = None) -> int:
        """Register the newest checkpoint a ``Checkpointer`` left in
        ``directory`` (serving straight off a training run's snapshots)."""
        from dryad_tpu.checkpoint import Checkpointer

        latest = Checkpointer(directory).latest()
        if latest is None:
            raise FileNotFoundError(f"no checkpoints in {directory!r}")
        booster, it = latest
        return self.add(booster, path=f"{directory}@{it}", activate=activate,
                        num_iteration=num_iteration, name=name)

    def add(self, booster: Booster, *, path: Optional[str] = None,
            activate: bool = True, num_iteration: Optional[int] = None,
            name: Optional[str] = None) -> int:
        with self._lock:
            version = self._next_version
            self._next_version += 1
            self._models[version] = ModelEntry(version, booster, path,
                                               num_iteration, name=name,
                                               registry=self)
            if name is not None:
                # latest add under a name wins — that's the deploy gesture
                self._aliases[str(name)] = version
            if activate or self._active is None:
                if self._active is not None:
                    self._history.append(self._active)
                self._active = version
            return version

    # ---- lifecycle ---------------------------------------------------------
    def activate(self, version: int) -> None:
        """Hot-swap the active version (must already be loaded)."""
        with self._lock:
            version = int(version)
            if version not in self._models:
                raise KeyError(f"model version {version} is not loaded")
            if version == self._active:
                return
            if self._active is not None:
                self._history.append(self._active)
            self._active = version

    def rollback(self) -> int:
        """Re-activate the previously active version; returns it."""
        with self._lock:
            while self._history:
                prev = self._history.pop()
                if prev in self._models:      # skip versions unloaded since
                    self._active = prev
                    return prev
            raise LookupError("no previous version to roll back to")

    def unload(self, version: int) -> None:
        with self._lock:
            version = int(version)
            if version == self._active:
                raise ValueError("cannot unload the active version; "
                                 "activate or rollback first")
            entry = self._models.pop(version, None)
            for alias, v in list(self._aliases.items()):
                if v == version:
                    del self._aliases[alias]
        if entry is not None:
            # free the staged/device arrays NOW: the registry forgets the
            # entry, so the budget's victim scan could never reach these
            # bytes again (a stale cache closure may still hold the entry
            # object, but a closed, empty one — and PredictServer.unload
            # also purges those closures)
            entry.closed = True
            entry.evict_staged()

    # ---- memory budget -----------------------------------------------------
    def _on_staged(self, entry: ModelEntry, restage: bool = False) -> None:
        """Budget enforcement hook, called by an entry right after it stages
        (outside the entry lock).  Victims are chosen under the registry
        lock but evicted outside it — an evicting thread must never hold
        the registry lock while waiting on an entry lock a concurrent
        stage holds (lock-order inversion)."""
        if restage and self.metrics is not None:
            self.metrics.record_restage(entry.version)
        if self.budget_bytes is None:
            return
        victims: list[ModelEntry] = []
        with self._lock:
            staged = [e for e in self._models.values() if e.staged_bytes > 0]
            total = sum(e.staged_bytes for e in staged)
            # LRU first; the active version and the just-staged entry are
            # pinned (evicting what we are about to predict with would
            # thrash the budget into a livelock)
            for e in sorted(staged, key=lambda e: e.last_used):
                if total <= self.budget_bytes:
                    break
                if e.version == self._active or e is entry:
                    continue
                victims.append(e)
                total -= e.staged_bytes
        for e in victims:
            if e.evict_staged() > 0 and self.metrics is not None:
                self.metrics.record_eviction(e.version)

    def memory(self) -> dict:
        """Budget observability: resident footprint + who is staged."""
        with self._lock:
            staged = {v: e.staged_bytes for v, e in self._models.items()
                      if e.staged_bytes > 0}
            return {
                "budget_bytes": self.budget_bytes,
                "staged_bytes": sum(staged.values()),
                "staged_versions": sorted(staged),
                # r21: which traversal layout each staged version runs
                "staged_layouts": {v: self._models[v].staged_layout
                                   for v in sorted(staged)},
            }

    # ---- lookup ------------------------------------------------------------
    def get(self, version: Optional[int] = None, *,
            name: Optional[str] = None) -> ModelEntry:
        with self._lock:
            if name is not None:
                if version is not None:
                    raise ValueError("pass either version or name, not both")
                version = self._aliases.get(str(name))
                if version is None:
                    raise KeyError(f"no model named {name!r}")
            if version is None:
                version = self._active
            if version is None:
                raise LookupError("registry has no models loaded")
            entry = self._models.get(int(version))
            if entry is None:
                raise KeyError(f"model version {version} is not loaded")
            self._tick += 1
            entry.last_used = self._tick
            return entry

    @property
    def active_version(self) -> Optional[int]:
        with self._lock:
            return self._active

    def versions(self) -> list[int]:
        with self._lock:
            return sorted(self._models)

    def aliases(self) -> dict:
        with self._lock:
            return dict(self._aliases)
