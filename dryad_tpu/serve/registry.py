"""Model registry: versioned boosters with hot-swap and rollback.

Every loaded model gets a monotonically increasing integer version.  One
version is *active* (the default for requests that don't pin a version);
``activate`` hot-swaps it and records the previous active version on a
history stack so ``rollback`` is one call.  In-flight requests resolve
their version at submit time, so a swap never changes a request that is
already queued.

An entry lazily stages its tree tables for the device predict path
(``engine.predict.stage_trees``) and keeps them device-resident across
requests — the staged arrays are uploaded once per (version, process),
then passed as *arguments* to the jitted accumulate (never closed over:
remote compile rejects large jit constants, see CLAUDE.md).
"""

from __future__ import annotations

import threading
from typing import Optional

from dryad_tpu.booster import Booster


class ModelEntry:
    """A registered model plus its lazily staged predict state."""

    def __init__(self, version: int, booster: Booster, path: Optional[str] = None,
                 num_iteration: Optional[int] = None):
        self.version = int(version)
        self.booster = booster
        self.path = path
        self.num_iteration = num_iteration
        self._lock = threading.Lock()
        self._staged = None      # (trees_np, init_np, n_iter)
        self._device = None      # (trees_dev, init_dev)

    @property
    def num_outputs(self) -> int:
        return self.booster.num_outputs

    def staged(self):
        """(trees, init, n_iter) reshaped numpy tables, built once."""
        with self._lock:
            if self._staged is None:
                from dryad_tpu.engine.predict import stage_trees

                self._staged = stage_trees(self.booster, self.num_iteration)
            return self._staged

    def device_state(self):
        """Device-resident (trees, init) for the jit predict path; uploaded
        once and reused by every bucket's compiled program."""
        trees_np, init_np, _ = self.staged()
        with self._lock:
            if self._device is None:
                import jax

                self._device = (
                    {k: jax.device_put(v) for k, v in trees_np.items()},
                    jax.device_put(init_np),
                )
            return self._device


class ModelRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._models: dict[int, ModelEntry] = {}
        self._active: Optional[int] = None
        self._history: list[int] = []   # previously active versions (for rollback)
        self._next_version = 1

    # ---- loading -----------------------------------------------------------
    def load(self, path: str, *, activate: bool = True,
             num_iteration: Optional[int] = None) -> int:
        """Register a model from disk — binary checkpoint or text dump,
        sniffed by content (Booster.load_any).  Returns its version."""
        return self.add(Booster.load_any(path), path=path, activate=activate,
                        num_iteration=num_iteration)

    def load_latest_checkpoint(self, directory: str, *, activate: bool = True,
                               num_iteration: Optional[int] = None) -> int:
        """Register the newest checkpoint a ``Checkpointer`` left in
        ``directory`` (serving straight off a training run's snapshots)."""
        from dryad_tpu.checkpoint import Checkpointer

        latest = Checkpointer(directory).latest()
        if latest is None:
            raise FileNotFoundError(f"no checkpoints in {directory!r}")
        booster, it = latest
        return self.add(booster, path=f"{directory}@{it}", activate=activate,
                        num_iteration=num_iteration)

    def add(self, booster: Booster, *, path: Optional[str] = None,
            activate: bool = True, num_iteration: Optional[int] = None) -> int:
        with self._lock:
            version = self._next_version
            self._next_version += 1
            self._models[version] = ModelEntry(version, booster, path,
                                               num_iteration)
            if activate or self._active is None:
                if self._active is not None:
                    self._history.append(self._active)
                self._active = version
            return version

    # ---- lifecycle ---------------------------------------------------------
    def activate(self, version: int) -> None:
        """Hot-swap the active version (must already be loaded)."""
        with self._lock:
            version = int(version)
            if version not in self._models:
                raise KeyError(f"model version {version} is not loaded")
            if version == self._active:
                return
            if self._active is not None:
                self._history.append(self._active)
            self._active = version

    def rollback(self) -> int:
        """Re-activate the previously active version; returns it."""
        with self._lock:
            while self._history:
                prev = self._history.pop()
                if prev in self._models:      # skip versions unloaded since
                    self._active = prev
                    return prev
            raise LookupError("no previous version to roll back to")

    def unload(self, version: int) -> None:
        with self._lock:
            version = int(version)
            if version == self._active:
                raise ValueError("cannot unload the active version; "
                                 "activate or rollback first")
            self._models.pop(version, None)

    # ---- lookup ------------------------------------------------------------
    def get(self, version: Optional[int] = None) -> ModelEntry:
        with self._lock:
            if version is None:
                version = self._active
            if version is None:
                raise LookupError("registry has no models loaded")
            entry = self._models.get(int(version))
            if entry is None:
                raise KeyError(f"model version {version} is not loaded")
            return entry

    @property
    def active_version(self) -> Optional[int]:
        with self._lock:
            return self._active

    def versions(self) -> list[int]:
        with self._lock:
            return sorted(self._models)
