"""dryad_tpu.serve — online inference on top of the bitwise-pinned predict.

    from dryad_tpu.serve import PredictServer

    server = PredictServer(backend="auto")      # CPU fallback if no device
    server.load_model("model.dryad")            # or the text dump
    preds = server.predict(X_rows)              # == Booster.predict, bitwise
    server.stats()                              # latency/batching/cache snapshot

Layers (each its own module):

* registry.py — versioned + named models, hot-swap + rollback,
                device-resident trees under an LRU memory budget
* cache.py    — shape-bucketed compiled-predict cache (pow2 row padding;
                single-device + sharded shard_map entry families)
* batcher.py  — micro-batching queue: deadline coalescing, backpressure,
                per-request timeouts, two-deep overlapped dispatch pipeline
* metrics.py  — counters + latency reservoir (global and per-model)
                behind ``stats()``
* server.py   — PredictServer tying the above together
* http.py     — stdlib HTTP front end (``python -m dryad_tpu serve``),
                structured request logging behind a flag
* bench.py    — closed-loop concurrency benchmark (scripts/bench_serve.py),
                pipeline-vs-serial compare + per-arm spread
"""

from dryad_tpu.serve.batcher import (MicroBatcher, Request, ServeOverloaded,
                                     ServeTimeout)
from dryad_tpu.serve.bench import run_bench, run_bench_compare
from dryad_tpu.serve.cache import (CompiledPredictCache, PreparedPredict,
                                   bucket_rows)
from dryad_tpu.serve.metrics import ModelStats, ServeMetrics
from dryad_tpu.serve.registry import ModelEntry, ModelRegistry
from dryad_tpu.serve.server import PredictServer

__all__ = [
    "CompiledPredictCache", "MicroBatcher", "ModelEntry", "ModelRegistry",
    "ModelStats", "PredictServer", "PreparedPredict", "Request",
    "ServeMetrics", "ServeOverloaded", "ServeTimeout", "bucket_rows",
    "run_bench", "run_bench_compare",
]
