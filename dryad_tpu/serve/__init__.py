"""dryad_tpu.serve — online inference on top of the bitwise-pinned predict.

    from dryad_tpu.serve import PredictServer

    server = PredictServer(backend="auto")      # CPU fallback if no device
    server.load_model("model.dryad")            # or the text dump
    preds = server.predict(X_rows)              # == Booster.predict, bitwise
    server.stats()                              # latency/batching/cache snapshot

Layers (each its own module):

* registry.py — versioned models, hot-swap + rollback, device-resident trees
* cache.py    — shape-bucketed compiled-predict cache (pow2 row padding)
* batcher.py  — micro-batching queue: deadline coalescing, backpressure,
                per-request timeouts
* metrics.py  — counters + latency reservoir behind ``stats()``
* server.py   — PredictServer tying the above together
* http.py     — stdlib HTTP front end (``python -m dryad_tpu serve``)
* bench.py    — closed-loop concurrency benchmark (scripts/bench_serve.py)
"""

from dryad_tpu.serve.batcher import (MicroBatcher, Request, ServeOverloaded,
                                     ServeTimeout)
from dryad_tpu.serve.bench import run_bench
from dryad_tpu.serve.cache import CompiledPredictCache, bucket_rows
from dryad_tpu.serve.metrics import ServeMetrics
from dryad_tpu.serve.registry import ModelEntry, ModelRegistry
from dryad_tpu.serve.server import PredictServer

__all__ = [
    "CompiledPredictCache", "MicroBatcher", "ModelEntry", "ModelRegistry",
    "PredictServer", "Request", "ServeMetrics", "ServeOverloaded",
    "ServeTimeout", "bucket_rows", "run_bench",
]
