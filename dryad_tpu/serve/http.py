"""Stdlib HTTP front end for PredictServer (no extra dependencies).

Endpoints (JSON in/out):

    POST /predict            {"rows": [[...], ...], "raw": false,
                              "version": null, "model": null,
                              "binned": false}
                             → {"predictions": [...], "version": v}
    GET  /stats              → PredictServer.stats() snapshot
    GET  /models             → {"active": v, "versions": [...],
                                "aliases": {...}}
    POST /models/load        {"path": "...", "activate": true,
                              "name": null} → {"version": v}
    POST /models/activate    {"version": v}
    POST /models/rollback    → {"version": v}
    GET  /metrics            → Prometheus text exposition of the shared
                               telemetry registry (dryad_tpu/obs)
    GET  /obs                → registry.snapshot() JSON (histogram counts
                               with bounds — the shape the fleet router
                               merges exactly across replicas, r17) plus
                               a "drift" block of raw window bin counts
                               per profiled model (r18; same exact-merge
                               discipline — counts, never ratios)
    GET  /trace              → Chrome trace_event JSON of the local span
                               ring (requires enable_tracing())
    GET  /trace/events       → raw ring events + a clock sample (the
                               fleet /trace assembly's per-replica feed)
    GET  /clock              → {"perf_s", "wall_s"} (auth-exempt: the
                               supervisor's clock-offset handshake at
                               replica registration)
    GET  /healthz            → 200 {"ok": true} | 503 {"ok": false,
                               "degraded": [...]} (obs/health.py; always
                               auth-exempt)

Request tracing (r17): ``X-Dryad-Trace`` on /predict is honored (minted
when absent and tracing is on) and echoed on the response; the id rides
the Request through the micro-batcher so the replica's queue-wait /
batch-assembly / predict spans land in the ring tagged with it.
``X-Dryad-Priority`` labels the per-(priority, stage) latency
histograms.  With obs disabled neither costs a per-request allocation.

Routing: ``version`` pins an exact registry version, ``model`` routes by
registry name (multi-model co-serving); default is the active version.

Bearer-token auth (``auth_token=`` / ``--auth-token`` / DRYAD_AUTH_TOKEN):
when set, every endpoint except ``/healthz`` requires ``Authorization:
Bearer <token>`` and answers 401 otherwise — the same scheme the
standalone metrics exporter applies (obs/exporter.py owns the check).

Structured request logging (off by default; ``log_requests=True`` or
``--log-requests`` on the CLI) emits one JSON line per request to
``log_stream``: method, path, status, resolved model version, row count,
and wall latency — greppable operational telemetry without a logging
dependency.

This is an operational front door, not a wire-speed RPC layer: requests
ride the same micro-batcher as in-process callers (ThreadingHTTPServer
gives one thread per connection, so concurrent POSTs coalesce into one
device dispatch), and numbers round-trip through JSON.  Bitwise-exact
transport belongs to the in-process API / npy files.
"""

from __future__ import annotations

import json
import sys
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from dryad_tpu.obs.registry import default_registry
from dryad_tpu.resilience.faults import InjectedReject
from dryad_tpu.serve.batcher import ServeOverloaded, ServeTimeout

TRACE_HEADER = "X-Dryad-Trace"
PRIORITY_HEADER = "X-Dryad-Priority"


class _Handler(BaseHTTPRequestHandler):
    # the PredictServer rides on the HTTP server object (see make_http_server)

    def _fire_fault(self, site: str) -> None:
        """The replica fault-drill hook (resilience/faults.py, r14): the
        fleet supervisor arms deterministic drills through the
        DRYAD_REPLICA_FAULTS env var and the serve CLI threads the decoded
        injector here.  Sites: one ``("request", n)`` per /predict, one
        ``("health", n)`` per /healthz probe.  May raise InjectedReject
        (mapped to 503 by the caller), sleep (slow_health), or hard-exit
        the process (replica_crash).  No hook, no cost."""
        hook = getattr(self.server, "fault_hook", None)
        if hook is None:
            return
        with self.server.fault_lock:
            n = self.server.fault_counts.get(site, 0) + 1
            self.server.fault_counts[site] = n
        hook(site, n)
    def _send(self, code: int, payload: dict,
              extra_headers: Optional[dict] = None) -> None:
        self._send_raw(code, json.dumps(payload).encode(),
                       "application/json", extra_headers)

    def _send_raw(self, code: int, body: bytes, ctype: str,
                  extra_headers: Optional[dict] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        if extra_headers:
            for k, v in extra_headers.items():
                self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)
        self._log_request(code)

    def _authorized(self) -> bool:
        """Bearer check for everything but /healthz; 401s on mismatch."""
        from dryad_tpu.obs.exporter import authorized, send_unauthorized

        if authorized(self, getattr(self.server, "auth_token", None)):
            return True
        # shared 401 with the metrics exporter (incl. WWW-Authenticate,
        # which RFC 7235 requires and a hand-rolled response here dropped)
        send_unauthorized(self)
        self._log_request(401)
        return False

    def _log_request(self, status: int) -> None:
        """One structured JSON line per completed request (flag-gated)."""
        if not getattr(self.server, "log_requests", False):
            return
        line = json.dumps({
            "ts": time.time(),
            "method": self.command,
            "path": self.path,
            "status": int(status),
            "version": getattr(self, "_req_version", None),
            "rows": getattr(self, "_req_rows", None),
            "latency_ms": round(
                (time.perf_counter() - getattr(self, "_req_t0",
                                               time.perf_counter())) * 1e3, 3),
        })
        stream = self.server.log_stream
        with self.server.log_lock:
            stream.write(line + "\n")
            stream.flush()

    def _read_json(self) -> dict:
        length = int(self.headers.get("Content-Length") or 0)
        if length == 0:
            return {}
        return json.loads(self.rfile.read(length).decode())

    def log_message(self, fmt, *args):  # quiet by default
        if getattr(self.server, "verbose", False):
            super().log_message(fmt, *args)

    def do_GET(self):  # noqa: N802 — stdlib handler API
        self._req_t0 = time.perf_counter()
        if self.path == "/healthz":
            # liveness probes skip auth; the shared health state flips this
            # (and the metrics exporter's /healthz) to 503 together — e.g.
            # an unexpected serve recompile after warmup (obs/tripwire.py)
            from dryad_tpu.obs.health import healthz_payload

            try:
                self._fire_fault("health")
            except InjectedReject as e:
                # the stuck-503 drill: a probe answer that LOOKS like a
                # latched degradation, without touching real health state
                self._send(503, {"ok": False, "degraded": ["injected"],
                                 "error": str(e)})
                return
            code, body = healthz_payload()
            self._send(code, body)
            return
        if self.path == "/clock":
            # auth-exempt like /healthz: the supervisor's clock-offset
            # handshake runs before any credential plumbing exists, and
            # the payload is two timestamps
            self._send(200, {"perf_s": time.perf_counter(),
                             "wall_s": time.time()})
            return
        if not self._authorized():
            return
        server = self.server.predict_server
        if self.path == "/stats":
            # the pre-obs snapshot shape, unchanged (acceptance-pinned):
            # the unified registry view lives on /metrics instead
            self._send(200, server.stats())
        elif self.path == "/metrics":
            self._send_raw(200, self.server.obs_registry.exposition().encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
        elif self.path == "/obs":
            doc = self.server.obs_registry.snapshot()
            # r18: the raw drift-window counts ride the same snapshot so
            # the fleet router's exact count-merge covers model-quality
            # telemetry too (absent when no model carries a profile or
            # drift is off — older routers simply never read the key)
            drift = server.drift_state()
            if drift:
                doc["drift"] = drift
            self._send(200, doc)
        elif self.path == "/trace":
            from dryad_tpu.obs import trace_export

            buf = trace_export.active_trace()
            self._send_raw(200, trace_export.dumps_trace(
                buf.events() if buf is not None else ()).encode(),
                "application/json")
        elif self.path == "/trace/events":
            from dryad_tpu.obs import trace_export

            buf = trace_export.active_trace()
            events, dropped = (buf.export() if buf is not None else ([], 0))
            self._send(200, {
                "events": [list(e) for e in events],
                "dropped": dropped,
                "clock": {"perf_s": time.perf_counter(),
                          "wall_s": time.time()},
            })
        elif self.path == "/models":
            self._send(200, {"active": server.registry.active_version,
                             "versions": server.registry.versions(),
                             "aliases": server.registry.aliases()})
        else:
            self._send(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):  # noqa: N802 — stdlib handler API
        self._req_t0 = time.perf_counter()
        if not self._authorized():
            return
        server = self.server.predict_server
        try:
            body = self._read_json()
            if self.path == "/predict":
                self._fire_fault("request")
                # propagated trace context: honor a supplied id; mint one
                # only while tracing is ON (the minting allocation is part
                # of the traced path, never the disabled one)
                trace = self.headers.get(TRACE_HEADER)
                priority = (self.headers.get(PRIORITY_HEADER)
                            or "interactive").lower()
                if priority not in ("interactive", "bulk"):
                    priority = "interactive"
                if trace is None:
                    from dryad_tpu.obs.trace_export import tracing_active

                    if tracing_active(self.server.obs_registry):
                        trace = uuid.uuid4().hex[:16]
                # resolve the entry up front: pre-binned rows must arrive in
                # the model's bin dtype (not float), and the response must
                # name the version that actually served — not whatever is
                # active by the time the batch returns
                entry = server.registry.get(body.get("version"),
                                            name=body.get("model"))
                self._req_version = entry.version
                binned = bool(body.get("binned", False))
                rows = np.asarray(body["rows"],
                                  entry.booster.mapper.bin_dtype if binned
                                  else np.float32)
                self._req_rows = int(rows.shape[0]) if rows.ndim > 1 else 1
                preds = server.predict(
                    rows,
                    version=entry.version,
                    raw_score=bool(body.get("raw", False)),
                    binned=binned,
                    timeout=body.get("timeout"),
                    trace=trace,
                    priority=priority,
                )
                self._send(200, {"predictions": np.asarray(preds).tolist(),
                                 "version": entry.version},
                           extra_headers=({TRACE_HEADER: trace}
                                          if trace else None))
            elif self.path == "/models/load":
                version = server.load_model(
                    body["path"], activate=bool(body.get("activate", True)),
                    name=body.get("name"))
                self._req_version = version
                self._send(200, {"version": version})
            elif self.path == "/models/activate":
                server.activate(int(body["version"]))
                self._req_version = int(body["version"])
                self._send(200, {"version": int(body["version"])})
            elif self.path == "/models/rollback":
                version = server.rollback()
                self._req_version = version
                self._send(200, {"version": version})
            else:
                self._send(404, {"error": f"unknown path {self.path}"})
        except InjectedReject as e:
            # the reject_503 drill answers exactly like queue overload
            self._send(503, {"error": str(e)})
        except ServeOverloaded as e:
            self._send(503, {"error": str(e)})
        except ServeTimeout as e:
            self._send(504, {"error": str(e)})
        except (KeyError, LookupError, ValueError) as e:
            self._send(400, {"error": repr(e)})
        except Exception as e:  # noqa: BLE001 — surface, don't kill the server
            self._send(500, {"error": repr(e)})


def make_http_server(predict_server, host: str = "127.0.0.1",
                     port: int = 8000, *, verbose: bool = False,
                     log_requests: bool = False,
                     log_stream=None, auth_token=None,
                     obs_registry=None, fault_hook=None) -> ThreadingHTTPServer:
    """Bind (port 0 picks a free one: ``httpd.server_address``); caller
    runs ``serve_forever()`` / ``shutdown()``.  ``auth_token`` turns on
    bearer auth (``/healthz`` exempt); ``obs_registry`` backs ``/metrics``
    (defaults to the process-wide registry serve already records into);
    ``fault_hook`` arms the replica fault drills (``resilience.faults``
    injector shape — the fleet supervisor wires it via the
    DRYAD_REPLICA_FAULTS env through the serve CLI)."""
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.predict_server = predict_server
    httpd.verbose = verbose
    httpd.log_requests = log_requests
    httpd.log_stream = log_stream if log_stream is not None else sys.stderr
    httpd.log_lock = threading.Lock()
    httpd.auth_token = auth_token
    httpd.fault_hook = fault_hook
    httpd.fault_lock = threading.Lock()
    httpd.fault_counts = {}
    httpd.obs_registry = (obs_registry if obs_registry is not None
                          else default_registry())
    predict_server.start()
    return httpd
